"""paddle.nn transformer layers.

Analog of /root/reference/python/paddle/nn/layer/transformer.py (2.0 API;
the reference's fused inference attention lives in
operators/fused/multihead_matmul_op.cu).  TPU-native: attention is expressed
as batched matmuls that XLA maps straight onto the MXU; a Pallas
flash-attention path (paddle_tpu.ops.pallas) can be toggled for long
sequences.
"""
from __future__ import annotations

import collections
import copy

import numpy as np

from ...dygraph.layers import Layer, LayerList
from .. import functional as F
from .common import Linear, Dropout
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer", "SwitchMoE"]


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    from ...tensor import math as M
    from ...tensor.manipulation import cast
    if attn_mask.dtype in ("bool", "int32", "int64"):
        # True/1 = keep; convert to additive mask
        m = cast(attn_mask, dtype)
        return M.scale(M.scale(m, -1.0, 1.0), -1e4)
    return attn_mask


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, L, D] -> [B, H, L, Dh]
        from ...tensor.manipulation import reshape, transpose
        b, l = x.shape[0], x.shape[1]
        x = reshape(x, [b, l, self.num_heads, self.head_dim])
        return transpose(x, [0, 2, 1, 3])

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ...tensor import math as M
        from ...tensor.manipulation import reshape, transpose, concat
        from ...tensor.linalg import matmul
        key = query if key is None else key
        value = query if value is None else value

        q = self._shape(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = concat([cache.k, k], axis=2)
                v = concat([cache.v, v], axis=2)
                cache = self.Cache(k, v)

        # flash path: Pallas blockwise kernel on the MXU (O(S) memory);
        # masked / weight-returning / dropout cases use the score matrix
        from ...ops.attention import use_flash_for
        if use_flash_for(int(q.shape[2])) and attn_mask is None and \
                not self.need_weights and \
                not (self.dropout and self.training):
            from ...ops.attention import flash_attention
            from ...dygraph.tracer import trace_jax
            out = trace_jax(
                lambda q_, k_, v_: flash_attention(q_, k_, v_),
                [q, k, v], "flash_attention")
            b, l = out.shape[0], out.shape[2]
            out = reshape(transpose(out, [0, 2, 1, 3]),
                          [b, l, self.embed_dim])
            out = self.out_proj(out)
            if cache is not None and isinstance(cache, self.Cache):
                return out, cache
            return out

        scores = M.scale(matmul(q, k, transpose_y=True),
                         scale=self.head_dim ** -0.5)
        mask = _convert_attention_mask(attn_mask, scores.dtype
                                       if hasattr(scores, "dtype")
                                       else "float32")
        if mask is not None:
            scores = M.add(scores, mask)
        weights = F.softmax(scores, axis=-1)
        if self.dropout:
            weights = F.dropout(weights, self.dropout,
                                training=self.training)
        out = matmul(weights, v)  # [B,H,L,Dh]
        b, l = out.shape[0], out.shape[2]
        out = reshape(transpose(out, [0, 2, 1, 3]),
                      [b, l, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and isinstance(cache, self.Cache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        from ...tensor.creation import zeros
        b = key.shape[0]
        k = zeros([b, self.num_heads, 0, self.head_dim])
        v = zeros([b, self.num_heads, 0, self.head_dim])
        return self.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self._activation = activation

    def forward(self, src, src_mask=None, cache=None):
        from ...tensor import math as M
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = M.add(residual, self.dropout1(src))
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        act = getattr(F, self._activation)
        src = self.linear2(self.act_dropout(act(self.linear1(src))))
        src = M.add(residual, self.dropout2(src))
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] +
            [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self._activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        from ...tensor import math as M
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = M.add(residual, self.dropout1(tgt))
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            if isinstance(tgt, tuple):
                tgt = tgt[0]
        tgt = M.add(residual, self.dropout2(tgt))
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        act = getattr(F, self._activation)
        tgt = self.linear2(self.act_dropout(act(self.linear1(tgt))))
        tgt = M.add(residual, self.dropout3(tgt))
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,))


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] +
            [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask,
                                        memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ...tensor.creation import tril, ones
        return tril(ones([length, length]))


class SwitchMoE(Layer):
    """Switch (top-1) Mixture-of-Experts feed-forward block as an
    nn.Layer (VERDICT r3: MoE as a framework citizen, not a demo) —
    shares the incubate/moe.py core through the `switch_moe` op, so the
    same code path serves dygraph, static capture (dy2static), and the
    ep-axis expert-parallel mesh executor.

    forward(x [..., d_model]) -> (out [..., d_model], aux_loss scalar);
    add `aux_weight * aux_loss` to the training loss (Switch
    Transformer load-balancing term)."""

    def __init__(self, d_model, d_hidden, num_experts,
                 capacity_factor=1.25, ep_ring_id=None, weight_attr=None,
                 name=None):
        super().__init__()
        from ...static.initializer import Normal
        from ...static.param_attr import ParamAttr
        self.capacity_factor = capacity_factor
        self.ep_ring_id = ep_ring_id

        def _sub_attr(suffix):
            return ParamAttr.derive(weight_attr, suffix)

        self.gate_w = self.create_parameter(
            [d_model, num_experts], attr=_sub_attr("_gate"),
            default_initializer=Normal(0.0, 0.02))
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        attr=_sub_attr("_w1"))
        self.b1 = self.create_parameter([num_experts, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        attr=_sub_attr("_w2"))
        self.b2 = self.create_parameter([num_experts, d_model],
                                        is_bias=True)

    def forward(self, x):
        from ...tensor._dispatch import dispatch
        attrs = {"capacity_factor": self.capacity_factor}
        if self.ep_ring_id is not None:
            attrs["ep_ring_id"] = int(self.ep_ring_id)
        return dispatch("switch_moe",
                        {"X": x, "GateW": self.gate_w, "W1": self.w1,
                         "B1": self.b1, "W2": self.w2, "B2": self.b2},
                        attrs, outs=["Out", "AuxLoss"])

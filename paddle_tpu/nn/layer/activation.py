"""paddle.nn activation layers (analog of python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from ...static.initializer import Constant
from .. import functional as F

__all__ = [
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "LeakyReLU", "PReLU", "ELU",
    "SELU", "Softmax", "LogSoftmax", "Softplus", "Softsign", "Softshrink",
    "Hardshrink", "Hardsigmoid", "Hardswish", "Swish", "Silu", "Mish",
    "Tanhshrink", "ThresholdedReLU", "LogSigmoid", "Maxout",
]


def _act_layer(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return fn(x, **fixed)

    _Act.__name__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Softplus = _act_layer("Softplus", F.softplus)
Softsign = _act_layer("Softsign", F.softsign)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardswish = _act_layer("Hardswish", F.hardswish)
Swish = _act_layer("Swish", F.swish)
Silu = _act_layer("Silu", F.silu)
Mish = _act_layer("Mish", F.mish)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)
SELU = _act_layer("SELU", F.selu)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)

"""paddle.nn RNN layers (analog of python/paddle/nn/layer/rnn.py).

The multi-layer LSTM/GRU/SimpleRNN forward runs the single `rnn` kernel
(ops/kernels/rnn.py), which lowers the whole time loop to one lax.scan —
XLA-friendly where the reference dispatched a C++ kernel per step.
"""
from __future__ import annotations

import math as _math

import numpy as np

from ...dygraph.layers import Layer
from ...static.initializer import Uniform
from ...tensor._dispatch import dispatch
from .. import functional as F

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0):
        from ...tensor.creation import full
        b = batch_ref.shape[0]
        return full([b, self.hidden_size], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / _math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        from ...tensor import math as M
        from ...tensor.linalg import matmul
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        i2h = M.add(matmul(inputs, self.weight_ih, transpose_y=True),
                    self.bias_ih)
        h2h = M.add(matmul(pre_h, self.weight_hh, transpose_y=True),
                    self.bias_hh)
        h = dispatch(self.activation, {"X": M.add(i2h, h2h)})
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / _math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        from ...tensor import math as M
        from ...tensor.manipulation import split
        from ...tensor.linalg import matmul
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        gates = M.add(
            M.add(matmul(inputs, self.weight_ih, transpose_y=True),
                  self.bias_ih),
            M.add(matmul(h, self.weight_hh, transpose_y=True), self.bias_hh))
        i, f, g, o = split(gates, 4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c_new = M.add(M.multiply(f, c), M.multiply(i, g))
        h_new = M.multiply(o, F.tanh(c_new))
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / _math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        from ...tensor import math as M
        from ...tensor.manipulation import split
        from ...tensor.linalg import matmul
        if states is None:
            states = self.get_initial_states(inputs)
        h = states
        x_g = M.add(matmul(inputs, self.weight_ih, transpose_y=True),
                    self.bias_ih)
        h_g = M.add(matmul(h, self.weight_hh, transpose_y=True),
                    self.bias_hh)
        xz, xr, xc = split(x_g, 3, axis=-1)
        hz, hr, hc = split(h_g, 3, axis=-1)
        z = F.sigmoid(M.add(xz, hz))
        r = F.sigmoid(M.add(xr, hr))
        c = F.tanh(M.add(xc, M.multiply(r, hc)))
        h_new = M.add(M.multiply(z, h),
                      M.multiply(M.scale(z, -1.0, 1.0), c))
        return h_new, h_new

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Run a cell over the time axis (python loop in eager; unrolls under
    trace — use the fused LSTM/GRU classes for long sequences)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack, unstack
        axis = 0 if self.time_major else 1
        xs = unstack(inputs, axis=axis)
        if self.is_reverse:
            xs = xs[::-1]
        states = initial_states
        outs = []
        for x in xs:
            out, states = self.cell(x, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer fused RNN over the `rnn` kernel (one lax.scan)."""

    _mode: str = None
    _gate_mult: int = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self._ndir = ndir
        g = self._gate_mult * hidden_size
        std = 1.0 / _math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._weights, self._biases = [], []
        wi = 0
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                w_ih = self.create_parameter([g, in_sz], weight_ih_attr,
                                             default_initializer=init)
                w_hh = self.create_parameter([g, hidden_size],
                                             weight_hh_attr,
                                             default_initializer=init)
                self.add_parameter(f"weight_ih_l{layer}{'_r' if d else ''}",
                                   w_ih)
                self.add_parameter(f"weight_hh_l{layer}{'_r' if d else ''}",
                                   w_hh)
                self._weights.extend([w_ih, w_hh])
        for layer in range(num_layers):
            for d in range(ndir):
                b_ih = self.create_parameter([g], bias_ih_attr, is_bias=True,
                                             default_initializer=init)
                b_hh = self.create_parameter([g], bias_hh_attr, is_bias=True,
                                             default_initializer=init)
                self.add_parameter(f"bias_ih_l{layer}{'_r' if d else ''}",
                                   b_ih)
                self.add_parameter(f"bias_hh_l{layer}{'_r' if d else ''}",
                                   b_hh)
                self._biases.extend([b_ih, b_hh])

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import transpose
        from ...tensor.creation import zeros
        x = inputs if self.time_major else transpose(inputs, [1, 0, 2])
        t, b = x.shape[0], x.shape[1]
        n = self.num_layers * self._ndir
        if initial_states is None:
            h0 = zeros([n, b, self.hidden_size])
            states = [h0]
            if self._mode == "LSTM":
                states.append(zeros([n, b, self.hidden_size]))
        else:
            states = (list(initial_states)
                      if isinstance(initial_states, (list, tuple))
                      else [initial_states])
        outs = dispatch(
            "rnn",
            {"Input": x, "PreState": states,
             "WeightList": self._weights + self._biases},
            {"mode": self._mode, "hidden_size": self.hidden_size,
             "num_layers": self.num_layers, "is_bidirec": self.bidirect,
             "dropout_prob": self.dropout},
            ["Out", "State", "Reserve", "DropoutState"])
        out, state = outs[0], outs[1]
        if not self.time_major:
            out = transpose(out, [1, 0, 2])
        if self._mode == "LSTM":
            return out, (state[0], state[1])
        return out, state[0]


class SimpleRNN(_RNNBase):
    _gate_mult = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        # instance-level mode so activation="relu" selects RNN_RELU
        self._mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(_RNNBase):
    _mode = "LSTM"
    _gate_mult = 4


class GRU(_RNNBase):
    _mode = "GRU"
    _gate_mult = 3

"""paddle.nn norm layers (analog of python/paddle/nn/layer/norm.py).

BatchNorm running stats are buffers updated in place by F.batch_norm in
eager mode; SyncBatchNorm adds a cross-replica mean/var allreduce over the
data-parallel mesh axis (reference: operators/sync_batch_norm_op.cu via
ir/sync_batch_norm_pass.cc — here it's one psum inside the kernel's mesh
context).
"""
from __future__ import annotations

import numpy as np

from ...dygraph.layers import Layer
from ...static.initializer import Constant
from .. import functional as F

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = "NCHW" if data_format in (
            "NC", "NCL", "NCHW", "NCDHW") else "NHWC"
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self._mean = self.register_buffer(
            "_mean", np.zeros([num_features], np.float32))
        self._variance = self.register_buffer(
            "_variance", np.ones([num_features], np.float32))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm(num_channels) (dygraph/nn.py BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from ...tensor._dispatch import dispatch
            out = dispatch(self._act, {"X": out})
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-device BN: batch statistics are allreduced over the "dp" mesh
    axis when run under a mesh context; identical to BatchNorm on 1 device."""

    def forward(self, x):
        from ...tensor._dispatch import dispatch, is_eager
        attrs = {"momentum": self._momentum, "epsilon": self._epsilon,
                 "data_format": self._data_format,
                 "is_test": not self.training}
        y, mean_out, var_out, _, _ = dispatch(
            "sync_batch_norm",
            {"X": x, "Scale": self.weight, "Bias": self.bias,
             "Mean": self._mean, "Variance": self._variance}, attrs,
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"])
        if self.training and hasattr(self._mean, "set_value"):
            self._mean.set_value(mean_out)
            self._variance.set_value(var_out)
        return y

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm* sublayers to SyncBatchNorm."""
        out = layer
        if isinstance(layer, _BatchNormBase) and \
                not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon)
            out.weight = layer.weight
            out.bias = layer.bias
            out._buffers = layer._buffers
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if np.isscalar(normalized_shape):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        n = int(np.prod(normalized_shape))
        self.weight = (self.create_parameter(
            [n], attr=weight_attr, default_initializer=Constant(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter([n], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter([num_channels], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        else:
            self.scale = self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        from ...tensor._dispatch import dispatch
        out, _ = dispatch("lrn", {"X": x},
                          {"n": self.size, "alpha": self.alpha,
                           "beta": self.beta, "k": self.k},
                          ["Out", "MidOut"])
        return out


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim, self._power_iters, self._eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=None)
        self.weight_v = self.create_parameter(
            [w], default_initializer=None)

    def forward(self, weight):
        from ...tensor._dispatch import dispatch
        return dispatch("spectral_norm",
                        {"Weight": weight, "U": self.weight_u,
                         "V": self.weight_v},
                        {"dim": self._dim, "power_iters": self._power_iters,
                         "eps": self._eps})

"""paddle.nn conv layers (analog of python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from ...dygraph.layers import Layer
from ...static.initializer import XavierInitializer
from .. import functional as F

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
           "Conv3DTranspose"]


def _pair(v, n=2):
    return [v] * n if np.isscalar(v) else list(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, weight_attr, bias_attr,
                 data_format, nd, transpose=False):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size, nd)
        self._stride = _pair(stride, nd)
        self._padding = padding
        self._dilation = _pair(dilation, nd)
        self._groups = groups or 1
        self._data_format = data_format
        if transpose:
            w_shape = [in_channels, out_channels // self._groups] + \
                self._kernel_size
        else:
            w_shape = [out_channels, in_channels // self._groups] + \
                self._kernel_size
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=XavierInitializer())
        self.bias = (self.create_parameter([out_channels], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1)

    def forward(self, x):
        # lift to 2d conv on a singleton height axis
        from ...tensor.manipulation import unsqueeze, squeeze
        x4 = unsqueeze(x, 2)
        w4 = self.weight.unsqueeze(2) if hasattr(self.weight, "unsqueeze") \
            else self.weight
        out = F.conv2d(x4, w4, self.bias,
                       stride=[1, self._stride[0]],
                       padding=[0, self._padding if np.isscalar(self._padding)
                                else self._padding[0]],
                       dilation=[1, self._dilation[0]], groups=self._groups)
        return squeeze(out, 2)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2, transpose=True)
        self._output_padding = output_padding

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3, transpose=True)

    def forward(self, x, output_size=None):
        from ...tensor._dispatch import dispatch
        attrs = {"strides": self._stride,
                 "paddings": _pair(self._padding, 3),
                 "dilations": self._dilation, "groups": self._groups,
                 "data_format": self._data_format}
        out = dispatch("conv3d_transpose",
                       {"Input": x, "Filter": self.weight}, attrs,
                       ["Output"])
        if self.bias is not None:
            out = dispatch("elementwise_add", {"X": out, "Y": self.bias},
                           {"axis": 1})
        return out

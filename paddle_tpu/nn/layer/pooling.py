"""paddle.nn pooling layers (analog of python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ...dygraph.layers import Layer
from .. import functional as F

__all__ = ["MaxPool2D", "AvgPool2D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D",
           "MaxPool1D", "AvgPool1D"]


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        super().__init__()
        self.ksize, self.stride, self.padding = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.ksize, self.stride, self.padding,
                            self.ceil_mode, self.return_mask,
                            self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.ksize, self.stride, self.padding = kernel_size, stride, padding
        self.ceil_mode, self.exclusive = ceil_mode, exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.ksize, self.stride, self.padding,
                            self.ceil_mode, self.exclusive, self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding, self.ceil_mode = padding, ceil_mode

    def forward(self, x):
        from ...tensor.manipulation import unsqueeze, squeeze
        out = F.max_pool2d(unsqueeze(x, 2), [1, self.ksize],
                           [1, self.stride], [0, self.padding],
                           self.ceil_mode)
        return squeeze(out, 2)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.ksize = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding, self.exclusive = padding, exclusive
        self.ceil_mode = ceil_mode

    def forward(self, x):
        from ...tensor.manipulation import unsqueeze, squeeze
        out = F.avg_pool2d(unsqueeze(x, 2), [1, self.ksize],
                           [1, self.stride], [0, self.padding],
                           self.ceil_mode, self.exclusive)
        return squeeze(out, 2)

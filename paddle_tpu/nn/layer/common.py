"""paddle.nn common layers.

Analog of /root/reference/python/paddle/nn/layer/common.py.
"""
from __future__ import annotations

import numpy as np

from ...dygraph.layers import Layer
from ...static.initializer import Uniform, Constant, Normal, XavierInitializer
from .. import functional as F

__all__ = ["Linear", "Dropout", "Dropout2D", "Embedding", "Flatten", "Pad2D",
           "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
           "PixelShuffle", "CosineSimilarity", "Identity", "Bilinear"]


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierInitializer())
        self.bias = (self.create_parameter([out_features], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self.weight.shape[0]}, "
                f"out_features={self.weight.shape[1]}")


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Dropout):
    pass


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:
            import jax.numpy as jnp
            w = self.weight._value
            self.weight._value = w.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding if not np.isscalar(padding) \
            else [padding] * 4
        self.mode, self.value, self.data_format = mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0,
                         data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0,
                         data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = (self.create_parameter([1, out_features], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x1, x2):
        from ...tensor._dispatch import dispatch
        out = dispatch("bilinear_tensor_product",
                       {"X": x1, "Y": x2, "Weight": self.weight,
                        "Bias": self.bias}, {})
        return out

"""paddle.nn.functional (dual-mode).

Analog of /root/reference/python/paddle/nn/functional/ — stateless forms of
the nn layers, dispatching through the shared kernel registry in both eager
and static mode.
"""
from __future__ import annotations

import numpy as np

from ..core.dtype import convert_dtype
from ..tensor._dispatch import dispatch

__all__ = []


def _export(fn, name=None):
    globals()[name or fn.__name__] = fn
    __all__.append(name or fn.__name__)
    return fn


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
_ACTS = ["relu", "relu6", "sigmoid", "tanh", "softplus", "softsign",
         "tanh_shrink", "silu", "mish", "selu", "swish", "hard_sigmoid",
         "hard_swish", "logsigmoid"]


def _make_act(op):
    def fn(x, name=None):
        return dispatch(op, {"X": x}, name=name)

    fn.__name__ = op
    return fn


for _a in _ACTS:
    _export(_make_act(_a))

_export(_make_act("logsigmoid"), "log_sigmoid")
_export(_make_act("hard_swish"), "hardswish")
_export(_make_act("hard_sigmoid"), "hardsigmoid")
_export(_make_act("tanh_shrink"), "tanhshrink")


@_export
def gelu(x, approximate=False, name=None):
    return dispatch("gelu", {"X": x}, {"approximate": bool(approximate)},
                    name=name)


@_export
def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch("leaky_relu", {"X": x}, {"alpha": float(negative_slope)},
                    name=name)


@_export
def elu(x, alpha=1.0, name=None):
    return dispatch("elu", {"X": x}, {"alpha": float(alpha)}, name=name)


@_export
def prelu(x, weight, name=None):
    mode = "all" if int(np.prod(weight.shape)) == 1 else "channel"
    return dispatch("prelu", {"X": x, "Alpha": weight}, {"mode": mode},
                    name=name)


@_export
def hardshrink(x, threshold=0.5, name=None):
    return dispatch("hard_shrink", {"X": x}, {"threshold": float(threshold)},
                    name=name)


@_export
def softshrink(x, threshold=0.5, name=None):
    return dispatch("softshrink", {"X": x}, {"lambda": float(threshold)},
                    name=name)


@_export
def thresholded_relu(x, threshold=1.0, name=None):
    return dispatch("thresholded_relu", {"X": x},
                    {"threshold": float(threshold)}, name=name)


@_export
def maxout(x, groups, axis=1, name=None):
    return dispatch("maxout", {"X": x}, {"groups": groups, "axis": axis},
                    name=name)


@_export
def softmax(x, axis=-1, dtype=None, name=None):
    out = dispatch("softmax", {"X": x}, {"axis": int(axis)}, name=name)
    return out


@_export
def log_softmax(x, axis=-1, dtype=None, name=None):
    return dispatch("log_softmax", {"X": x}, {"axis": int(axis)}, name=name)


@_export
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..tensor import random as R
    from ..tensor import math as M
    u = R.uniform(x.shape, "float32", 1e-20, 1.0 - 1e-7)
    g = M.scale(M.log(M.scale(M.log(u), scale=-1.0)), scale=-1.0)
    y = softmax(M.scale(M.add(x, g), scale=1.0 / temperature), axis=axis)
    if hard:
        from ..tensor import search as S
        from ..tensor.manipulation import transpose
        nd = len(y.shape)
        ax = axis % nd
        idx = S.argmax(y, axis=ax, keepdim=True)
        hard_y = dispatch("one_hot_v2", {"X": idx.squeeze(ax)},
                          {"depth": y.shape[ax]})
        if ax != nd - 1:
            # one_hot appends depth last; move it back to `axis`
            perm = list(range(nd - 1))
            perm.insert(ax, nd - 1)
            hard_y = transpose(hard_y, perm)
        # straight-through estimator
        y = M.add(M.subtract(hard_y, y.detach()
                             if hasattr(y, "detach") else y), y)
    return y


# ---------------------------------------------------------------------------
# linear / conv / pool
# ---------------------------------------------------------------------------
@_export
def linear(x, weight, bias=None, name=None):
    out = dispatch("matmul_v2", {"X": x, "Y": weight},
                   {"trans_x": False, "trans_y": False}, name=name)
    if bias is not None:
        out = dispatch("elementwise_add", {"X": out, "Y": bias},
                       {"axis": -1})
    return out


def _pair(v, n=2):
    return [v] * n if np.isscalar(v) else list(v)


@_export
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    attrs = {"strides": _pair(stride), "paddings": _pair(padding),
             "dilations": _pair(dilation), "groups": groups,
             "data_format": data_format}
    if isinstance(padding, str):
        attrs["paddings"] = [0, 0]
        attrs["padding_algorithm"] = padding.upper()
    out = dispatch("conv2d", {"Input": x, "Filter": weight}, attrs,
                   ["Output"], name=name)
    if bias is not None:
        caxis = 1 if data_format.startswith("NC") else -1
        out = dispatch("elementwise_add", {"X": out, "Y": bias},
                       {"axis": caxis})
    return out


@_export
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW", name=None):
    attrs = {"strides": _pair(stride), "paddings": _pair(padding),
             "dilations": _pair(dilation), "groups": groups,
             "data_format": data_format}
    out = dispatch("conv2d_transpose", {"Input": x, "Filter": weight},
                   attrs, ["Output"], name=name)
    if bias is not None:
        caxis = 1 if data_format.startswith("NC") else -1
        out = dispatch("elementwise_add", {"X": out, "Y": bias},
                       {"axis": caxis})
    return out


@_export
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    attrs = {"strides": _pair(stride, 3), "paddings": _pair(padding, 3),
             "dilations": _pair(dilation, 3), "groups": groups,
             "data_format": data_format}
    out = dispatch("conv3d", {"Input": x, "Filter": weight}, attrs,
                   ["Output"], name=name)
    if bias is not None:
        caxis = 1 if data_format.startswith("NC") else -1
        out = dispatch("elementwise_add", {"X": out, "Y": bias},
                       {"axis": caxis})
    return out


@_export
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    attrs = {"pooling_type": "max", "ksize": ks,
             "strides": _pair(stride) if stride is not None else ks,
             "paddings": _pair(padding), "ceil_mode": ceil_mode,
             "global_pooling": False, "data_format": data_format,
             "exclusive": True}
    if return_mask:
        return dispatch("max_pool2d_with_index", {"X": x}, attrs,
                        ["Out", "Mask"], name=name)
    return dispatch("pool2d", {"X": x}, attrs, name=name)


@_export
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    attrs = {"pooling_type": "avg", "ksize": ks,
             "strides": _pair(stride) if stride is not None else ks,
             "paddings": _pair(padding), "ceil_mode": ceil_mode,
             "global_pooling": False, "data_format": data_format,
             "exclusive": exclusive}
    return dispatch("pool2d", {"X": x}, attrs, name=name)


@_export
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    attrs = {"pooling_type": "avg", "ksize": _pair(output_size),
             "adaptive": True, "strides": [1, 1], "paddings": [0, 0],
             "global_pooling": False, "data_format": data_format,
             "exclusive": True}
    return dispatch("pool2d", {"X": x}, attrs, name=name)


@_export
def adaptive_max_pool2d(x, output_size, data_format="NCHW", name=None):
    attrs = {"pooling_type": "max", "ksize": _pair(output_size),
             "adaptive": True, "strides": [1, 1], "paddings": [0, 0],
             "global_pooling": False, "data_format": data_format,
             "exclusive": True}
    return dispatch("pool2d", {"X": x}, attrs, name=name)


# ---------------------------------------------------------------------------
# norm / dropout / embedding
# ---------------------------------------------------------------------------
@_export
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    n_norm = 1 if np.isscalar(normalized_shape) else len(normalized_shape)
    bna = len(x.shape) - n_norm
    out, _, _ = dispatch("layer_norm",
                         {"X": x, "Scale": weight, "Bias": bias},
                         {"epsilon": epsilon, "begin_norm_axis": bna},
                         ["Y", "Mean", "Variance"], name=name)
    return out


@_export
def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    attrs = {"momentum": momentum, "epsilon": epsilon,
             "data_format": data_format, "is_test": not training,
             "use_global_stats": bool(use_global_stats)
             if use_global_stats is not None else False}
    y, mean_out, var_out, _, _, _ = dispatch(
        "batch_norm",
        {"X": x, "Scale": weight, "Bias": bias, "Mean": running_mean,
         "Variance": running_var}, attrs,
        ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance",
         "ReserveSpace"], name=name)
    # eager: write back running stats in place (the static path wires
    # MeanOut/VarianceOut to the same persistable vars)
    if training and hasattr(running_mean, "set_value"):
        running_mean.set_value(mean_out)
        running_var.set_value(var_out)
    return y


@_export
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, eps=1e-5, momentum=0.9, name=None):
    out, _, _ = dispatch("instance_norm",
                         {"X": x, "Scale": weight, "Bias": bias},
                         {"epsilon": eps},
                         ["Y", "SavedMean", "SavedVariance"], name=name)
    return out


@_export
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    out, _, _ = dispatch("group_norm",
                         {"X": x, "Scale": weight, "Bias": bias},
                         {"epsilon": epsilon, "groups": num_groups,
                          "data_format": data_format},
                         ["Y", "Mean", "Variance"], name=name)
    return out


@_export
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    from ..tensor import math as M
    if p == 2:
        attrs = {"axis": int(axis), "epsilon": float(epsilon),
                 "is_test": True}
        out, _ = dispatch("norm", {"X": x}, attrs, ["Out", "Norm"],
                          name=name)
        return out
    pn = dispatch("p_norm", {"X": x},
                  {"porder": float(p), "axis": int(axis), "keepdim": True,
                   "epsilon": float(epsilon)})
    return M.divide(x, pn)


@_export
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    impl = mode if mode in ("upscale_in_train",
                            "downgrade_in_infer") else "upscale_in_train"
    out, _ = dispatch("dropout", {"X": x},
                      {"dropout_prob": float(p), "is_test": not training,
                       "dropout_implementation": impl},
                      ["Out", "Mask"], name=name)
    return out


@_export
def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p, training=training, name=name)


@_export
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return dispatch("lookup_table_v2", {"W": weight, "Ids": x},
                    {"padding_idx": -1 if padding_idx is None
                     else padding_idx}, name=name)


@_export
def one_hot(x, num_classes, name=None):
    return dispatch("one_hot_v2", {"X": x}, {"depth": int(num_classes)},
                    name=name)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def _reduce_loss(loss, reduction):
    from ..tensor import math as M
    if reduction == "mean":
        return M.mean(loss)
    if reduction == "sum":
        return M.sum(loss)
    return loss


@_export
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    loss, _ = dispatch(
        "softmax_with_cross_entropy",
        {"Logits": input, "Label": label},
        {"soft_label": bool(soft_label), "ignore_index": ignore_index,
         "axis": int(axis), "use_softmax": use_softmax},
        ["Loss", "Softmax"], name=name)
    if weight is not None and not soft_label:
        from ..tensor import math as M
        from ..tensor.manipulation import gather, squeeze, unsqueeze
        lbl = label if len(label.shape) == len(loss.shape) else label
        w = gather(weight, squeeze(lbl, -1)
                   if lbl.shape[-1] == 1 else lbl)
        w = unsqueeze(w, -1) if len(w.shape) < len(loss.shape) else w
        loss = M.multiply(loss, w)
        if reduction == "mean":
            from ..tensor import math as M2
            return M2.divide(M2.sum(loss), M2.sum(w))
    return _reduce_loss(loss, reduction)


@_export
def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100,
                               return_softmax=False, name=None):
    loss, sm = dispatch(
        "softmax_with_cross_entropy", {"Logits": logits, "Label": label},
        {"soft_label": bool(soft_label), "ignore_index": ignore_index,
         "axis": int(axis)}, ["Loss", "Softmax"], name=name)
    return (loss, sm) if return_softmax else loss


@_export
def mse_loss(input, label, reduction="mean", name=None):
    loss = dispatch("mse_loss", {"X": input, "Y": label}, name=name)
    return _reduce_loss(loss, reduction)


@_export
def l1_loss(input, label, reduction="mean", name=None):
    from ..tensor import math as M
    loss = M.abs(M.subtract(input, label))
    return _reduce_loss(loss, reduction)


@_export
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    loss, _ = dispatch("nll_loss", {"X": input, "Label": label,
                                    "Weight": weight},
                       {"ignore_index": ignore_index,
                        "reduction": reduction}, ["Out", "Total_weight"],
                       name=name)
    return loss


@_export
def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    loss = dispatch("bce_loss", {"X": input, "Label": label}, name=name)
    if weight is not None:
        from ..tensor import math as M
        loss = M.multiply(loss, weight)
    return _reduce_loss(loss, reduction)


@_export
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    loss = dispatch("sigmoid_cross_entropy_with_logits",
                    {"X": logit, "Label": label},
                    {"ignore_index": -100, "normalize": False}, name=name)
    if weight is not None:
        from ..tensor import math as M
        loss = M.multiply(loss, weight)
    return _reduce_loss(loss, reduction)


@_export
def kl_div(input, label, reduction="mean", name=None):
    loss = dispatch("kldiv_loss", {"X": input, "Target": label},
                    {"reduction": "none"}, name=name)
    return _reduce_loss(loss, reduction)


@_export
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    loss = dispatch("huber_loss", {"X": input, "Y": label},
                    {"delta": float(delta)}, name=name)
    return _reduce_loss(loss, reduction)


@_export
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    from ..tensor import math as M
    out = M.multiply(M.subtract(other, input), label)
    out = M.clip(M.add(out, M.scale(out, 0.0) + margin), min=0.0)
    return _reduce_loss(out, reduction)


@_export
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    from ..tensor import math as M
    p = sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = M.add(M.multiply(p, label),
                M.multiply(M.scale(p, -1.0, 1.0),
                           M.scale(label, -1.0, 1.0)))
    mod = M.pow(M.scale(p_t, -1.0, 1.0), gamma)
    a_t = M.add(M.scale(label, alpha),
                M.scale(M.scale(label, -1.0, 1.0), 1 - alpha))
    loss = M.multiply(M.multiply(a_t, mod), ce)
    if normalizer is not None:
        loss = M.divide(loss, normalizer)
    return _reduce_loss(loss, reduction)


# ---------------------------------------------------------------------------
# shape/pad/vision ops
# ---------------------------------------------------------------------------
@_export
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    nd = len(x.shape)
    if len(pad) == nd * 2:
        paddings = list(pad)
    else:
        # paddle/torch semantics: the pad list applies LAST dim first —
        # [left, right, top, bottom] pads W by (l, r) then H by (t, b)
        paddings = [0] * (nd * 2)
        pairs = list(zip(pad[0::2], pad[1::2]))
        for j, (lo, hi) in enumerate(pairs):
            dim = nd - 1 - j
            paddings[2 * dim] = int(lo)
            paddings[2 * dim + 1] = int(hi)
    return dispatch("pad", {"X": x},
                    {"paddings": paddings, "pad_value": float(value),
                     "mode": mode}, name=name)


@_export
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    op = {"nearest": "nearest_interp_v2", "bilinear": "bilinear_interp_v2",
          "bicubic": "bicubic_interp_v2", "trilinear": "trilinear_interp",
          "linear": "linear_interp"}[mode]
    attrs = {"align_corners": align_corners, "align_mode": align_mode,
             "data_layout": data_format}
    if size is not None:
        size = _pair(size)
        attrs["out_h"], attrs["out_w"] = int(size[0]), int(size[1])
    if scale_factor is not None:
        attrs["scale"] = (float(scale_factor)
                          if np.isscalar(scale_factor)
                          else list(scale_factor))
    return dispatch(op, {"X": x}, attrs, name=name)


upsample = interpolate
_export(interpolate, "upsample")


@_export
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return dispatch("pixel_shuffle", {"X": x},
                    {"upscale_factor": upscale_factor,
                     "data_format": data_format}, name=name)


@_export
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return dispatch("im2sequence", {"X": x},
                    {"kernels": _pair(kernel_sizes),
                     "strides": _pair(strides),
                     "paddings": _pair(paddings, 4)}, name=name)


@_export
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return dispatch("grid_sampler", {"X": x, "Grid": grid},
                    {"mode": mode, "padding_mode": padding_mode,
                     "align_corners": align_corners}, ["Output"], name=name)


@_export
def affine_grid(theta, out_shape, align_corners=True, name=None):
    return dispatch("affine_grid", {"Theta": theta},
                    {"output_shape": list(out_shape),
                     "align_corners": align_corners}, ["Output"], name=name)


@_export
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    from ..tensor import math as M
    x1n = normalize(x1, axis=axis, epsilon=eps)
    x2n = normalize(x2, axis=axis, epsilon=eps)
    return M.sum(M.multiply(x1n, x2n), axis=axis)


@_export
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    from ..tensor import math as M
    n = label.shape[-1]
    smoothed = M.scale(label, 1.0 - epsilon)
    if prior_dist is not None:
        return M.add(smoothed, M.scale(prior_dist, epsilon))
    return M.add(smoothed, M.scale(M.scale(label, 0.0), 0.0) +
                 (epsilon / n))


@_export
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    return dispatch("sequence_mask", {"X": x},
                    {"maxlen": -1 if maxlen is None else int(maxlen),
                     "out_dtype": convert_dtype(dtype)}, ["Y"], name=name)


@_export
def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return dispatch("temporal_shift", {"X": x},
                    {"seg_num": seg_num, "shift_ratio": shift_ratio},
                    name=name)

"""paddle_tpu.nn — the paddle-2.0 neural-net API.

Analog of /root/reference/python/paddle/nn/ (P7 in SURVEY.md §2.2): Layer
classes over the dygraph module system + functional forms; all compute goes
through the shared kernel registry.
"""
from ..dygraph.layers import (  # noqa: F401
    Layer, Sequential, LayerList, ParameterList,
)
from ..dygraph.base import no_grad  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import (  # noqa: F401
    common, conv, pooling, norm, activation, loss, rnn, transformer,
)

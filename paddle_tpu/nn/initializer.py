"""paddle.nn.initializer — 2.0 names over the shared initializer classes
(analog of python/paddle/nn/initializer/)."""
from ..static.initializer import (  # noqa: F401
    Bilinear,
    Constant, Uniform, Normal, TruncatedNormal, Xavier,
    XavierInitializer, MSRA, MSRAInitializer, NumpyArrayInitializer,
    Assign, set_global_initializer,
)

XavierNormal = XavierInitializer


class XavierUniform(XavierInitializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in, fan_out=fan_out)


class KaimingNormal(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=False, fan_in=fan_in)


class KaimingUniform(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in)

"""paddle.regularizer (reference python/paddle/regularizer.py): the 2.0
top-level regularizer names."""
from .static.optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]

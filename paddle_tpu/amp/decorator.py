"""Static-graph AMP optimizer decorator with dynamic loss scaling.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
decorator.py — `decorate` (:215) wraps an optimizer in
OptimizerWithMixedPrecision: rewrite_program casts the forward, the loss is
scaled before backward, `check_finite_and_unscale` + `update_loss_scaling`
ops guard the optimizer step.

TPU note: with bfloat16 the exponent range matches fp32, so dynamic loss
scaling is rarely required — `use_dynamic_loss_scaling=False` +
init_loss_scaling=1.0 is the recommended TPU configuration; the full fp16
machinery is kept for parity.
"""
from __future__ import annotations

from ..core.program import OpRole, default_startup_program, unique_name
from ..static import layers
from ..static.layer_helper import LayerHelper
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program

__all__ = ["decorate", "OptimizerWithMixedPrecision"]


class OptimizerWithMixedPrecision:
    """decorator.py:37 parity."""

    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 dest_dtype="bfloat16"):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dest_dtype = dest_dtype
        self._loss_scaling = None
        self._scaled_loss = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def get_scaled_loss(self):
        return self._scaled_loss

    def _create_scale_vars(self):
        self._loss_scaling = layers.create_global_var(
            [1], self._init_loss_scaling, "float32", persistable=True,
            name=unique_name("loss_scaling"))
        if self._use_dynamic_loss_scaling:
            self._good_steps = layers.create_global_var(
                [1], 0, "int32", persistable=True,
                name=unique_name("good_steps"))
            self._bad_steps = layers.create_global_var(
                [1], 0, "int32", persistable=True,
                name=unique_name("bad_steps"))

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        """decorator.py:142 — rewrite program, scale loss, backward."""
        program = loss.block.program
        from ..core.program import program_guard
        with program_guard(program, startup_program
                           or default_startup_program()):
            rewrite_program(program, self._amp_lists, self._dest_dtype)
            # loss may now be low precision; bring it to fp32 for scaling
            if loss.dtype != "float32":
                loss = layers.cast(loss, "float32")
            self._create_scale_vars()
            with program._op_role_guard(OpRole.Forward):
                self._scaled_loss = layers.elementwise_mul(
                    loss, self._loss_scaling)
            params_grads = self._optimizer.backward(
                self._scaled_loss, startup_program, parameter_list,
                no_grad_set, callbacks)
        return params_grads

    def apply_gradients(self, params_grads):
        """decorator.py:167 — unscale & inf-check before the real step."""
        program = params_grads[0][0].block.program
        from ..core.program import program_guard
        with program_guard(program), \
                program._op_role_guard(OpRole.Optimize):
            grads = [g for _, g in params_grads]
            helper = LayerHelper("check_finite_and_unscale")
            found_inf = helper.create_variable_for_type_inference("bool")
            outs = [helper.block.create_var(
                name=unique_name(g.name + "@UNSCALED"), shape=g.shape,
                dtype=g.dtype, stop_gradient=True) for g in grads]
            helper.append_op(
                "check_finite_and_unscale",
                inputs={"X": grads, "Scale": [self._loss_scaling]},
                outputs={"Out": outs, "FoundInfinite": [found_inf]})
            if self._use_dynamic_loss_scaling:
                outs2 = [helper.block.create_var(
                    name=unique_name(g.name + "@GUARDED"), shape=g.shape,
                    dtype=g.dtype, stop_gradient=True) for g in grads]
                helper.append_op(
                    "update_loss_scaling",
                    inputs={"X": outs, "FoundInfinite": [found_inf],
                            "PrevLossScaling": [self._loss_scaling],
                            "InGoodSteps": [self._good_steps],
                            "InBadSteps": [self._bad_steps]},
                    outputs={"Out": outs2,
                             "LossScaling": [self._loss_scaling],
                             "OutGoodSteps": [self._good_steps],
                             "OutBadSteps": [self._bad_steps]},
                    attrs={"incr_every_n_steps": self._incr_every_n_steps,
                           "decr_every_n_nan_or_inf":
                               self._decr_every_n_nan_or_inf,
                           "incr_ratio": self._incr_ratio,
                           "decr_ratio": self._decr_ratio})
                outs = outs2
            new_pg = [(p, g) for (p, _), g in zip(params_grads, outs)]
        return self._optimizer.apply_gradients(new_pg)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        ops = self.apply_gradients(params_grads)
        # recorded like Optimizer.minimize does: the PS transpiler and
        # static.gradient_merge read the pairing off the program
        loss.block.program._ps_params_grads = params_grads
        return ops, params_grads

    def __getattr__(self, item):
        return getattr(self.__dict__["_optimizer"], item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, dest_dtype="bfloat16"):
    """contrib/mixed_precision/decorator.py:215 parity."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest_dtype)

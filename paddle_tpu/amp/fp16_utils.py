"""Program rewriting for static-graph AMP: cast insertion.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_utils.py — `rewrite_program` walks the block, classifying each op
white/black/gray and inserting `cast` ops so white ops consume fp16 and
black ops consume fp32.

TPU design notes: the casts are pure dataflow ops that XLA fuses into the
adjacent matmul/conv (free on the MXU path), so we insert per-use casts and
keep parameters fp32 (master weights) rather than maintaining fp16 parameter
copies like `cast_parameters_to_fp16`.
"""
from __future__ import annotations

from typing import Dict

from ..core.program import Program, Block, OpDesc, OpRole, unique_name
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["rewrite_program", "cast_model_to_fp16"]

_FLOAT = ("float32", "float64")

# Slot-level dtype semantics: these output slots stay fp32 regardless of
# the op's precision decision (their kernels always emit fp32 — statistics
# and loss values), so the rewrite must not declare them low-precision.
_FP32_OUT_SLOTS = {
    "softmax_with_cross_entropy": {"Loss"},
    "layer_norm": {"Mean", "Variance"},
}

# Gray ops whose kernels upcast internally and accept fp32 parameters
# alongside low-precision activations (layer_norm casts Scale/Bias to the
# compute dtype itself) — persistable float inputs don't block the
# low-precision decision and are left as fp32 master weights.
_PARAM_TOLERANT = {"layer_norm"}

# Gray ops whose kernels FOLLOW one MAIN operand's dtype under mixed
# operands instead of promoting (softmax_with_cross_entropy returns
# softmax/loss in the Logits dtype and upcasts the label internally —
# ops/kernels/loss.py): a black fp32 SECONDARY operand (a label-smooth
# target) doesn't force the whole op — and its giant output — back to
# fp32.  When the MAIN operand (the value of this map) is already
# low-precision, the op is decided low, black operands stay protected
# (uncast), and the output declarations match what the kernel actually
# emits; when the main operand itself is black/fp32, black-wins
# applies as usual (the kernel follows it to fp32).  Promoting binaries
# (elementwise_add etc.) are deliberately NOT here: their kernel output
# under mixed operands IS fp32, so black-wins keeps declarations
# truthful for them.
_MIXED_FOLLOW = {"softmax_with_cross_entropy": "Logits"}


def _is_float_var(block, name):
    try:
        v = block.var(name)
    except KeyError:
        return False
    return v.dtype in _FLOAT or v.dtype in ("float16", "bfloat16")


def _insert_cast(block, name, src_dtype, dst_dtype, cache, new_ops, uid_fn):
    key = (name, dst_dtype)
    if key in cache:
        return cache[key]
    out = unique_name(f"{name}.cast_{dst_dtype}")
    block.create_var(name=out, shape=block.var(name).shape, dtype=dst_dtype,
                     stop_gradient=block.var(name).stop_gradient)
    op = OpDesc("cast", {"X": [name]}, {"Out": [out]},
                {"in_dtype": src_dtype, "out_dtype": dst_dtype,
                 OpRole.KEY: OpRole.Forward, "op_uid": uid_fn()})
    new_ops.append(op)
    cache[key] = out
    return out


def rewrite_program(main_program: Program, amp_lists=None,
                    dest_dtype: str = "bfloat16"):
    """fp16_utils.py rewrite_program parity (forward block only — call
    BEFORE append_backward, as decorate() does)."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    block = main_program.global_block()
    var_dtype: Dict[str, str] = {}  # rewritten dtype of each var
    black_out = set()  # vars produced by black ops — fp32 for a REASON
    new_ops = []
    cache: Dict = {}
    uid_fn = main_program._next_uid

    for op in block.ops:
        if op.op_role != OpRole.Forward and op.op_role != OpRole.Loss:
            new_ops.append(op)
            continue
        t = op.type
        if t in amp_lists.white_list and not (
                amp_lists.black_varnames &
                set(op.input_names() + op.output_names())):
            want = dest_dtype
        elif t in amp_lists.gray_list:
            # reference gray semantics (fp16_utils.py _rewrite): a black
            # producer wins (its fp32 output is protected — don't cast it
            # back down); otherwise follow any low-precision producer,
            # casting the remaining float inputs (e.g. the fp32 bias param
            # of an fc's bias-add); with neither, stay fp32.  Exception:
            # a _MIXED_FOLLOW kernel fed by BOTH (bf16 logits + a black
            # fp32 label) runs mixed and follows the low operand, so it
            # is decided low with the black operand left uncast — the
            # verifier's V103 catches the stale-fp32 alternative.
            ins = [n for n in op.input_names() if _is_float_var(block, n)]
            low = any(var_dtype.get(n, block.var(n).dtype) == dest_dtype
                      for n in ins)
            # follower exception keys on the MAIN operand specifically:
            # a bf16 label with black fp32 logits must NOT flip the op
            # low (the kernel would follow the fp32 logits)
            follow_low = False
            if t in _MIXED_FOLLOW:
                follow_low = any(
                    var_dtype.get(n, block.var(n).dtype) == dest_dtype
                    for n in op.inputs.get(_MIXED_FOLLOW[t], [])
                    if n and _is_float_var(block, n))
            if any(n in black_out for n in ins) and not follow_low:
                want = None
                black_out.update(
                    n for n in op.output_names()
                    if _is_float_var(block, n))
            elif low:
                want = dest_dtype
            else:
                want = None
        else:
            want = "float32"
            black_out.update(n for n in op.output_names()
                             if _is_float_var(block, n))

        if want is not None:
            for slot, names in op.inputs.items():
                out_names = []
                for n in names:
                    if not _is_float_var(block, n) or (
                            t in _PARAM_TOLERANT and
                            block.var(n).persistable) or (
                            t in amp_lists.gray_list and n in black_out):
                        # on a low-decided GRAY op a black-produced fp32
                        # operand stays protected (the kernel upcasts it
                        # internally); white ops still cast everything
                        # down — running the matmul in bf16 is their job
                        out_names.append(n)
                        continue
                    cur = var_dtype.get(n, block.var(n).dtype)
                    if cur in _FLOAT + ("float16", "bfloat16") and cur != want:
                        out_names.append(_insert_cast(
                            block, n, cur, want, cache, new_ops, uid_fn))
                    else:
                        out_names.append(n)
                op.inputs[slot] = out_names
            fp32_slots = _FP32_OUT_SLOTS.get(t, ())
            for slot, names in op.outputs.items():
                for n in names:
                    if not _is_float_var(block, n):
                        continue
                    if slot in fp32_slots:
                        block.var(n).dtype = "float32"
                        var_dtype[n] = "float32"
                    else:
                        block.var(n).dtype = want
                        var_dtype[n] = want
        new_ops.append(op)
    block.ops = new_ops
    main_program._fingerprint_cache = None
    from ..core.pass_framework import finish_pass
    finish_pass(main_program, "amp", dest_dtype=dest_dtype)
    return main_program


def cast_model_to_fp16(program: Program, amp_lists=None,
                       dest_dtype: str = "bfloat16"):
    """fp16_utils.py cast_model_to_fp16 (pure-fp16 mode O2): every float var
    and op flipped to the low dtype except the black list."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    lists = AutoMixedPrecisionLists(
        custom_white_list=amp_lists.gray_list | amp_lists.white_list,
        custom_black_list=amp_lists.black_list)
    return rewrite_program(program, lists, dest_dtype)

"""Auto-mixed-precision op lists.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_lists.py — AutoMixedPrecisionLists with white (run in fp16), black
(keep fp32), gray (follow inputs) op sets.

TPU note: the low-precision dtype defaults to bfloat16 (the MXU's native
input dtype); fp16 is accepted for parity.  The lists below use THIS
framework's op names (ops/registry) — MXU-bound ops (matmul/conv) are white,
numerically sensitive reductions (softmax-with-loss, norms, exp/log) black.
"""
from __future__ import annotations

import copy

__all__ = ["AutoMixedPrecisionLists", "white_list", "black_list", "gray_list"]

# Ops that gain from bf16 on the MXU (fp16_lists.py white_list analog)
white_list = {
    "matmul", "matmul_v2", "mul", "fc", "bmm", "mv",
    "conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
    "depthwise_conv2d",
    # Pallas attention kernels: MXU-bound, fp32 accumulation inside
    "flash_attention", "ring_attention",
}

# Numerically sensitive — keep fp32 (fp16_lists.py black_list analog)
black_list = {
    "exp", "log", "log1p", "square", "rsqrt",
    "cross_entropy",
    "cross_entropy2", "bce_loss", "nll_loss", "sigmoid_cross_entropy_with_logits",
    "mean", "reduce_mean", "reduce_sum", "sum",
    "batch_norm", "sync_batch_norm", "instance_norm",
    "group_norm", "norm", "p_norm", "frobenius_norm", "squared_l2_norm",
    "cos_sim", "kldiv_loss", "huber_loss", "smooth_l1_loss",
    "cumsum", "logsumexp", "erf",
}

# Dtype follows the inputs (fp16_lists.py gray_list analog)
gray_list = {
    # these kernels upcast to fp32 INTERNALLY (loss.py _compute_dtype,
    # nn.py softmax/layer_norm, activation.py log_softmax), so bf16
    # activations reach them directly — same math as black-listing, minus
    # the materialized fp32 casts of the largest tensors in an LM step
    # (logits, attention scores, residual-stream layer_norm inputs) and
    # their fp32 cotangents
    "softmax_with_cross_entropy", "softmax", "log_softmax", "layer_norm",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "relu", "gelu", "sigmoid", "tanh", "relu6",
    "leaky_relu", "swish", "hard_swish", "prelu", "maximum", "minimum",
    "pool2d", "pool3d", "reshape2", "reshape", "transpose2", "transpose",
    "concat", "split", "slice", "stack", "unstack", "squeeze", "unsqueeze",
    "squeeze2", "unsqueeze2", "flatten", "flatten2", "dropout", "pad",
    "pad2d", "pad3d", "expand", "expand_v2", "tile", "gather", "gather_nd",
    "scatter", "scale", "clip", "bilinear_interp", "nearest_interp",
    "flatten_contiguous_range",
}


class AutoMixedPrecisionLists:
    """fp16_lists.py AutoMixedPrecisionLists parity: user deltas applied to
    the defaults; everything not white/gray is treated as black."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = copy.copy(white_list)
        self.black_list = copy.copy(black_list)
        self.gray_list = copy.copy(gray_list)
        self.black_varnames = set(custom_black_varnames or ())
        if custom_white_list:
            for op in custom_white_list:
                self.white_list.add(op)
                self.black_list.discard(op)
                self.gray_list.discard(op)
        if custom_black_list:
            for op in custom_black_list:
                self.black_list.add(op)
                self.white_list.discard(op)
                self.gray_list.discard(op)
        if self.white_list & self.black_list:
            raise ValueError("op appears in both custom white and black "
                             f"lists: {self.white_list & self.black_list}")

"""Dygraph AMP: auto_cast context + GradScaler.

Reference: /root/reference/paddle/fluid/imperative/amp_auto_cast.cc (the
dygraph tracer casts op inputs by white/black list when the AMP guard is on)
and python/paddle/fluid/dygraph/amp/loss_scaler.py (GradScaler analog:
scale loss, unscale grads, skip the step on inf/nan, adapt the scale).

TPU note: dtype defaults to bfloat16 (MXU-native).  The cast hook lives in
dygraph/tracer.trace_op, which calls `amp_cast_inputs` on every eager op —
the same interception point as the reference tracer
(imperative/tracer.cc CastPureFp16Inputs).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict

import jax.numpy as jnp

from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["auto_cast", "amp_guard", "GradScaler", "amp_cast_inputs",
           "amp_state"]


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.level = "O1"
        self.dtype = "bfloat16"
        self.lists = None


_state = _AmpState()


def amp_state():
    return _state


@contextlib.contextmanager
def amp_guard(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    prev = (_state.enabled, _state.level, _state.dtype, _state.lists)
    _state.enabled = bool(enable) and level != "O0"
    _state.level = level
    _state.dtype = dtype
    _state.lists = AutoMixedPrecisionLists(custom_white_list,
                                           custom_black_list)
    try:
        yield
    finally:
        (_state.enabled, _state.level, _state.dtype, _state.lists) = prev


auto_cast = amp_guard  # paddle.amp.auto_cast 2.0 spelling


def _cast(v, dtype):
    if v is None:
        return v
    a = jnp.asarray(v)
    if jnp.issubdtype(a.dtype, jnp.floating) and str(a.dtype) != dtype:
        return a.astype(dtype)
    return v


def amp_cast_inputs(op_type: str, raw_ins: Dict[str, Any]):
    """Called from trace_op on every eager op while the guard is active."""
    if not _state.enabled:
        return raw_ins
    lists = _state.lists or AutoMixedPrecisionLists()
    if _state.level == "O2":
        low = op_type not in lists.black_list
        dtype = _state.dtype if low else "float32"
    elif op_type in lists.white_list:
        dtype = _state.dtype
    elif op_type in lists.black_list:
        dtype = "float32"
    else:
        return raw_ins
    out = {}
    for slot, v in raw_ins.items():
        if isinstance(v, list):
            out[slot] = [_cast(x, dtype) for x in v]
        else:
            out[slot] = _cast(v, dtype)
    return out


class GradScaler:
    """paddle.amp.GradScaler / fluid dygraph AmpScaler parity."""

    def __init__(self, enable=True, init_loss_scaling=2 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False
        self._unscaled = False  # guards against double division per step

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def _unscale_and_check(self, optimizer):
        """Divide grads by the scale; detect non-finite values.  Raises on a
        second unscale in the same step (the reference AmpScaler contract).
        One finite-ness scalar accumulates on device; a single host sync at
        the end (not one blocking round-trip per parameter)."""
        if self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        self._unscaled = True
        params = getattr(optimizer, "_parameter_list", None)
        if not params:
            # 2.0 Optimizer exposes the list as the `_params` property
            params = getattr(optimizer, "_params", None)
            if callable(params):
                params = params()
        if not params:
            raise ValueError("optimizer has no parameters to unscale")
        inv = 1.0 / self._scale
        all_finite = jnp.asarray(True)
        for p in params:
            g = p.grad
            if g is None:
                continue
            raw = g._value if hasattr(g, "_value") else jnp.asarray(g)
            raw = raw.astype(jnp.float32) * inv
            all_finite = all_finite & jnp.all(jnp.isfinite(raw))
            if hasattr(g, "_value"):
                g._value = raw
            else:
                p.grad = raw
        self._found_inf = not bool(all_finite)  # single device→host sync

    def minimize(self, optimizer, scaled_loss, *args, **kwargs):
        """Unscale, skip-on-inf, step, update the dynamic scale."""
        if not self._enable:
            return optimizer.minimize(scaled_loss, *args, **kwargs)
        self.step(optimizer, scaled_loss, *args, **kwargs)
        self._update()
        return None

    def step(self, optimizer, scaled_loss=None, *args, **kwargs):
        """2.0 GradScaler.step: unscale (if not yet) and apply the optimizer
        step unless non-finite grads were found.  Does NOT advance the
        dynamic scale — pair with update(), or use minimize()."""
        if not self._enable:
            if hasattr(optimizer, "step"):
                return optimizer.step()
            return optimizer.minimize(scaled_loss, *args, **kwargs)
        if not self._unscaled:
            self._unscale_and_check(optimizer)
        if not self._found_inf:
            if hasattr(optimizer, "step"):
                optimizer.step()
            else:
                optimizer.minimize(scaled_loss, *args, **kwargs)

    def unscale_(self, optimizer):
        self._unscale_and_check(optimizer)

    def update(self):
        self._update()

    def _update(self):
        self._unscaled = False
        if not self._use_dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good = 0
        self._found_inf = False  # consumed; next step re-detects

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good,
                "bad_steps": self._bad,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf}

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good = d.get("good_steps", 0)
        self._bad = d.get("bad_steps", 0)
        self._incr_ratio = d.get("incr_ratio", self._incr_ratio)
        self._decr_ratio = d.get("decr_ratio", self._decr_ratio)
        self._incr_every_n_steps = d.get("incr_every_n_steps",
                                         self._incr_every_n_steps)
        self._decr_every_n_nan_or_inf = d.get("decr_every_n_nan_or_inf",
                                              self._decr_every_n_nan_or_inf)


AmpScaler = GradScaler  # fluid dygraph spelling

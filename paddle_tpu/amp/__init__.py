"""paddle.amp — automatic mixed precision (static + dygraph).

Static path: `decorate(optimizer)` (contrib/mixed_precision/decorator.py:215
analog) rewrites the program with bf16 casts and adds loss-scaling ops.
Dygraph path: `auto_cast()` guard + `GradScaler`
(imperative/amp_auto_cast.cc + dygraph/amp/loss_scaler.py analogs).
"""
from .fp16_lists import AutoMixedPrecisionLists, white_list, black_list, \
    gray_list  # noqa: F401
from .fp16_utils import rewrite_program, cast_model_to_fp16  # noqa: F401
from .decorator import decorate, OptimizerWithMixedPrecision  # noqa: F401
from .auto_cast import (  # noqa: F401
    auto_cast, amp_guard, GradScaler, AmpScaler, amp_cast_inputs, amp_state,
)

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate",
           "AutoMixedPrecisionLists", "rewrite_program", "cast_model_to_fp16"]

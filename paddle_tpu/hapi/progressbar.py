"""Terminal progress bar (reference: python/paddle/hapi/progressbar.py)."""
from __future__ import annotations

import sys
import time

__all__ = ["ProgressBar"]


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self._file = file
        self._start = time.time()
        self._last_update = 0.0

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        values = values or []
        now = time.time()
        msg = ""
        if self._num is not None:
            msg += f"step {current_num}/{self._num}"
            if self._verbose == 1:
                frac = min(1.0, current_num / max(1, self._num))
                filled = int(frac * self._width)
                bar = "=" * filled + ">" + "." * (self._width - filled - 1)
                msg += f" [{bar[:self._width]}]"
        else:
            msg += f"step {current_num}"
        for k, v in values:
            try:
                msg += f" - {k}: {float(v):.4f}"
            except (TypeError, ValueError):
                msg += f" - {k}: {v}"
        elapsed = now - self._start
        msg += f" - {elapsed:.0f}s"
        if self._verbose == 1:
            self._file.write("\r" + msg)
            if self._num is not None and current_num >= self._num:
                self._file.write("\n")
        else:
            if now - self._last_update > 1 or (
                    self._num is not None and current_num >= self._num):
                self._file.write(msg + "\n")
                self._last_update = now
        self._file.flush()

"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler callback)."""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .progressbar import ProgressBar

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # hook surface (callbacks.py parity)
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


class ProgBarLogger(Callback):
    """callbacks.py ProgBarLogger parity."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")
        self._bar = ProgressBar(self.steps, verbose=self.verbose)

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and (step + 1) % self.log_freq == 0:
            self._bar.update(step + 1, list(logs.items()))

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        if self.verbose:
            self._bar.update(self.steps or 0, list(logs.items()))


class ModelCheckpoint(Callback):
    """callbacks.py ModelCheckpoint: save every `save_freq` epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and \
                (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """callbacks.py EarlyStopping parity (monitors an eval metric)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 min_delta=0, baseline=None, save_best_model=True,
                 save_dir=None):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.save_dir = save_dir
        self.wait = 0
        self.best = baseline
        self.stopped_epoch = 0
        self._epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode

    def _better(self, cur, ref):
        if self.mode == "min":
            return cur < ref - self.min_delta
        return cur > ref + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        self._epoch = epoch

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None \
                    and self.save_dir:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self._epoch
                if self.model is not None:
                    self.model.stop_training = True


class LRSchedulerCallback(Callback):
    """Steps the optimizer's LRScheduler each epoch (callbacks.py
    LRScheduler)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, log_freq=1, save_freq=1, save_dir=None,
                     metrics=None, force_params=True):
    """callbacks.py config_callbacks: assemble the default list.

    force_params=False (nested evaluate/predict inside fit) only sets
    params on callbacks that don't have any yet, so a user callback shared
    with the outer fit keeps its epochs/steps configuration.
    """
    user = list(callbacks or [])
    cbks = list(user)
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.insert(0, ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks):
        cbks.append(LRSchedulerCallback())
    lst = CallbackList(cbks)
    lst.set_model(model)
    params = {"epochs": epochs, "steps": steps, "verbose": verbose,
              "metrics": metrics or []}
    for c in lst.callbacks:
        if force_params or not c.params:
            c.set_params(params)
    return lst

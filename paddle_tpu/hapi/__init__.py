"""paddle.hapi — high-level Model API (reference: python/paddle/hapi/)."""
from .model import Model, Input, summary  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping,
)
from .progressbar import ProgressBar  # noqa: F401

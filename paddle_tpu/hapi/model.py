"""paddle.Model — the Keras-like high-level train/eval/predict API.

Reference: /root/reference/python/paddle/hapi/model.py:788 `Model` with
`fit` (:1242), `evaluate`, `predict`, `train_batch`/`eval_batch`,
`save`/`load`, `summary`; Input specs from hapi; static+dynamic adapters.

TPU note: this implementation drives the dygraph engine (each batch is an
eager step over jitted kernels); for the big jit-everything path use the
static API (`paddle_tpu.static`) or wrap the Layer with
`paddle_tpu.jit.to_static`.  Multi-device data parallelism composes via
`paddle_tpu.distributed.DataParallel` around the network.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..dygraph.layers import Layer
from ..dygraph.tensor import Tensor, to_tensor
from .callbacks import config_callbacks

__all__ = ["Model", "Input", "summary"]


class Input:
    """hapi Input spec (name/shape/dtype), used for summary and
    save_inference parity."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"Input(name={self.name}, shape={self.shape}, " \
               f"dtype={self.dtype})"


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _make_loader(data, batch_size, shuffle, drop_last, num_workers):
    from ..io import DataLoader, Dataset
    if data is None:
        return None
    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    raise TypeError(f"train_data must be Dataset or DataLoader, "
                    f"got {type(data)}")


class Model:
    """hapi/model.py:788 parity (dygraph adapter)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._amp = False
        self._amp_level = "O1"
        self.stop_training = False

    # -- prepare ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, use_jit=False):
        """use_jit=True compiles forward+loss into ONE jitted XLA
        computation per input signature (paddle_tpu.jit.StaticFunction):
        loss.backward() then runs the compiled vjp instead of the per-op
        tape walk — the whole-block fast path for 2.0-API training."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._amp = amp_configs is not None
        self._amp_level = (amp_configs or {}).get("level", "O1") \
            if isinstance(amp_configs, dict) else "O1"
        self._use_jit = bool(use_jit)
        # one StaticFunction per inputs/labels split — the split is baked
        # into each trace, so it must be part of what selects the trace
        self._jit_fns = {}
        return self

    def _jit_fn_for(self, n_in: int):
        fn = self._jit_fns.get(n_in)
        if fn is None:
            from ..jit import StaticFunction

            def fwd_loss(*args):
                outs = _to_list(self.network(*args[:n_in]))
                lbls = list(args[n_in:])
                loss_t = self._loss(*(outs + lbls))
                return tuple([loss_t] + outs)

            fn = StaticFunction(fwd_loss, layer=self.network)
            self._jit_fns[n_in] = fn
        return fn

    # -- single-batch ops ----------------------------------------------------
    def _forward(self, inputs):
        ins = [to_tensor(np.asarray(x)) if not isinstance(x, Tensor) else x
               for x in _to_list(inputs)]
        out = self.network(*ins)
        return out

    def _compute_loss(self, outputs, labels):
        labels = [to_tensor(np.asarray(y)) if not isinstance(y, Tensor)
                  else y for y in _to_list(labels)]
        outs = _to_list(outputs)
        if self._loss is None:
            raise RuntimeError("prepare(loss=...) required for training")
        return self._loss(*(outs + labels)), outs, labels

    def _jit_step(self, inputs, labels):
        ins = [to_tensor(np.asarray(x)) if not isinstance(x, Tensor) else x
               for x in _to_list(inputs)]
        lbls = [to_tensor(np.asarray(y)) if not isinstance(y, Tensor)
                else y for y in _to_list(labels)]
        res = self._jit_fn_for(len(ins))(*(ins + lbls))
        return res[0], list(res[1:]), lbls

    def _loss_outs(self, inputs, labels):
        """(loss, outs, labels) via the jit or eager path, AMP applied to
        either (jit: the casts are baked into the trace)."""
        from contextlib import nullcontext
        if self._amp:
            from ..amp import auto_cast
            cm = auto_cast(level=self._amp_level)
        else:
            cm = nullcontext()
        with cm:
            if getattr(self, "_use_jit", False):
                if self._loss is None:
                    raise RuntimeError(
                        "prepare(loss=...) required for training")
                return self._jit_step(inputs, labels)
            outputs = self._forward(inputs)
        return self._compute_loss(outputs, labels)

    def train_batch(self, inputs, labels=None, update=True):
        """hapi model.py train_batch: one fwd/bwd/step."""
        self.network.train()
        loss, outs, lbls = self._loss_outs(inputs, labels)
        loss.backward()
        if update and self._optimizer is not None:
            if hasattr(self._optimizer, "step"):
                self._optimizer.step()
                self._optimizer.clear_grad()
            else:  # fluid-style
                self._optimizer.minimize(loss)
                self.network.clear_gradients()
        metrics = self._update_metrics(outs, lbls)
        return [float(np.asarray(loss.numpy()))] + metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..dygraph.base import no_grad
        with no_grad():
            loss, outs, lbls = self._loss_outs(inputs, labels)
        metrics = self._update_metrics(outs, lbls)
        return [float(np.asarray(loss.numpy()))] + metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..dygraph.base import no_grad
        with no_grad():
            out = self._forward(inputs)
        return [np.asarray(o.numpy()) for o in _to_list(out)]

    def _update_metrics(self, outs, lbls):
        """Metric protocol parity (metric/metrics.py): compute(pred, label)
        → intermediate(s) → update(*intermediates)."""
        vals = []
        for m in self._metrics:
            raw = [np.asarray(t.numpy()) if hasattr(t, "numpy")
                   else np.asarray(t) for t in (outs + lbls)]
            inter = m.compute(*raw)
            if not isinstance(inter, (list, tuple)):
                inter = (inter,)
            m.update(*inter)
            acc = m.accumulate()
            accs = acc if isinstance(acc, (list, tuple)) else [acc]
            vals.extend(float(np.asarray(a).reshape(-1)[0]) for a in accs)
        return vals

    def _metric_names(self):
        names = []
        for m in self._metrics:
            n = m.name() if callable(getattr(m, "name", None)) else str(m)
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names

    # -- fit / evaluate / predict -------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, resume=False):
        """hapi model.py:1242 parity, plus preemption-safe auto-resume.

        With ``save_dir`` set, every ``save_freq``-th epoch commits an
        atomic checkpoint (params + optimizer + LR state) under
        ``<save_dir>/checkpoints`` via paddle_tpu.checkpoint.
        ``resume=True`` restores the newest valid checkpoint and
        continues from the epoch after it — a killed run re-launched
        with the same arguments picks up where it stopped.

        Preemption (docs/elastic.md): with ``save_dir`` set, a SIGTERM
        mid-training commits the LAST COMPLETED epoch's state as a
        final synchronous checkpoint before the process dies — even for
        epochs ``save_freq`` skipped — so a preempted fit resumes at
        that epoch boundary and the partial epoch replays (the same
        round-down semantics as the static elastic tier).  The chaos
        harness (``PADDLE_TPU_CHAOS`` kill directives, counted in
        train batches here) covers this loop too."""
        loader = _make_loader(train_data, batch_size, shuffle, drop_last,
                              num_workers)
        eval_loader = _make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        start_epoch = 0
        ckpt_mgr = None
        if resume and save_dir is None:
            raise ValueError("fit(resume=True) needs save_dir")
        if save_dir is not None:
            from ..checkpoint import CheckpointManager
            ckpt_root = os.path.join(save_dir, "checkpoints")
            if not resume and os.path.isdir(ckpt_root):
                # a previous run's higher-numbered checkpoints would make
                # retention GC delete this fresh run's commits the moment
                # they land, and would hijack a later resume=True — a
                # non-resuming fit owns its save_dir
                import shutil
                import warnings
                warnings.warn(
                    f"fit(resume=False) discarding stale checkpoints "
                    f"under {ckpt_root}", RuntimeWarning, stacklevel=2)
                shutil.rmtree(ckpt_root)
            ckpt_mgr = CheckpointManager(ckpt_root)
            if resume:
                ckpt = ckpt_mgr.load()
                if ckpt is not None:
                    self._restore_fit_state(ckpt)
                    start_epoch = ckpt.step + 1
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, verbose=verbose,
                                log_freq=log_freq, save_freq=save_freq,
                                save_dir=save_dir,
                                metrics=["loss"] + self._metric_names())
        self.stop_training = False
        cbks.on_train_begin()
        history = []
        # preemption: SIGTERM commits the newest EPOCH-BOUNDARY state
        # (cached below after every epoch, not just save_freq ones) —
        # a mid-epoch snapshot would resume at epoch+1 with half an
        # epoch of extra updates baked in
        epoch_cache = [None]
        if ckpt_mgr is not None:
            ckpt_mgr.set_state_provider(lambda: epoch_cache[0])
            ckpt_mgr.install_preemption_handler()
        from ..testing import chaos as _chaos
        batches_done = 0
        try:
            for epoch in range(start_epoch, epochs):
                if self.stop_training:
                    break
                for m in self._metrics:
                    m.reset()
                cbks.on_epoch_begin(epoch)
                logs = {}
                for step, batch in enumerate(loader):
                    cbks.on_train_batch_begin(step)
                    ins, lbls = self._split_batch(batch)
                    res = self.train_batch(ins, lbls)
                    logs = dict(zip(["loss"] + self._metric_names(), res))
                    cbks.on_train_batch_end(step, logs)
                    batches_done += 1
                    _chaos.step_hook(batches_done)
                cbks.on_epoch_end(epoch, logs)
                history.append(logs)
                if ckpt_mgr is not None:
                    state, extra = self._fit_state()
                    extra["epoch"] = epoch
                    # cache BY VALUE: the state dict holds the live
                    # parameter tensors, and the preemption save happens
                    # batches later — an aliased cache would commit a
                    # mid-epoch chimera labeled as this epoch
                    epoch_cache[0] = (
                        epoch,
                        {k: np.array(v.numpy()) if hasattr(v, "numpy")
                         else np.array(v) for k, v in state.items()},
                        extra)
                    if (epoch + 1) % save_freq == 0 or \
                            epoch + 1 == epochs:
                        ckpt_mgr.save(epoch, state, extra=extra)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, batch_size=batch_size,
                                  verbose=0, callbacks=callbacks)
        finally:
            if ckpt_mgr is not None:
                import sys
                # must be read BEFORE the except below, which would make
                # exc_info reflect the close error instead
                propagating = sys.exc_info()[0] is not None
                try:
                    ckpt_mgr.close()  # drains in-flight saves
                except Exception:
                    # a deferred background-save error must not mask an
                    # exception already propagating out of the train loop
                    if not propagating:
                        raise
                    import warnings
                    warnings.warn(
                        "checkpoint manager close failed while another "
                        "exception was propagating", RuntimeWarning)
        cbks.on_train_end()
        return history

    def _fit_state(self):
        """(state, extra) for the epoch checkpoint: tensors prefixed
        model/ and opt/; the JSON-able LR-scheduler dict rides extra."""
        state = {"model/" + k: v for k, v in
                 self.network.state_dict().items()}
        from ..core.generator import get_rng_state
        # without the generator state a resumed run would redraw dropout
        # masks / shuffles from a fresh counter and diverge from the
        # straight-through run
        extra = {"rng": get_rng_state()}
        if self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            for k, v in self._optimizer.state_dict().items():
                if k == "LR_Scheduler":
                    extra["lr_scheduler"] = {
                        kk: float(vv) for kk, vv in v.items()}
                else:
                    state["opt/" + k] = v
        return state, extra

    def _restore_fit_state(self, ckpt):
        params = {k[len("model/"):]: v for k, v in ckpt.state.items()
                  if k.startswith("model/")}
        self.network.set_state_dict(params)
        opt_state = {k[len("opt/"):]: v for k, v in ckpt.state.items()
                     if k.startswith("opt/")}
        if "rng" in ckpt.extra:
            from ..core.generator import set_rng_state
            set_rng_state(ckpt.extra["rng"])
        if "lr_scheduler" in ckpt.extra:
            opt_state["LR_Scheduler"] = ckpt.extra["lr_scheduler"]
        if opt_state and self._optimizer is not None and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(opt_state)

    def _split_batch(self, batch):
        batch = _to_list(batch)
        n_in = max(1, len(self._inputs)) if self._inputs else 1
        if len(batch) == 1:
            return batch, []
        if self._inputs:
            return batch[:n_in], batch[n_in:]
        return batch[:-1] if len(batch) > 1 else batch, batch[-1:]

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = _make_loader(eval_data, batch_size, False, False,
                              num_workers)
        for m in self._metrics:
            m.reset()
        cbks = config_callbacks(callbacks, model=self, steps=None,
                                verbose=verbose,
                                metrics=["loss"] + self._metric_names(),
                                force_params=False)
        cbks.on_eval_begin()
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            ins, lbls = self._split_batch(batch)
            res = self.eval_batch(ins, lbls)
            losses.append(res[0])
            logs = dict(zip(["loss"] + self._metric_names(), res))
        logs["loss"] = float(np.mean(losses)) if losses else 0.0
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=0):
        loader = _make_loader(test_data, batch_size, False, False,
                              num_workers)
        outputs: List[List[np.ndarray]] = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outs = self.predict_batch(ins)
            outputs.append(outs)
        # transpose: list over outputs, each a list over batches
        n_out = len(outputs[0]) if outputs else 0
        per_out = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            per_out = [np.concatenate(o, axis=0) for o in per_out]
        return per_out

    # -- save / load / summary ----------------------------------------------
    def save(self, path, training=True):
        """model.py save: <path>.pdparams (+ .pdopt when training).  Both
        files go through the atomic write-temp-then-rename helper — a
        crash mid-write leaves the previous artifact intact instead of a
        truncated pickle."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..io.framework_io import save_dygraph
        save_dygraph(self.network.state_dict(), path)
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            def _host(v):
                # arrays → numpy; nested dicts (LR_Scheduler state) kept
                return v if hasattr(v, "keys") else np.asarray(v)
            from ..checkpoint.atomic import atomic_write
            with atomic_write(path + ".pdopt") as f:
                pickle.dump({k: _host(v) for k, v in
                             self._optimizer.state_dict().items()},
                            f, protocol=4)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..io.framework_io import load_dygraph
        params, _ = load_dygraph(path)
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            with open(opt_path, "rb") as f:
                self._optimizer.set_state_dict(pickle.load(f))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size or
                       [i.shape for i in self._inputs] or None)


def summary(net: Layer, input_size=None, dtypes=None):
    """hapi summary: layer table + parameter counts.  Weight-tied params
    (e.g. BERT's MLM decoder sharing the word embedding) count once."""
    rows = []
    total = 0
    trainable = 0
    seen = set()
    for name, sub in net.named_sublayers(include_self=True):
        n_params = 0
        for p in sub.parameters(include_sublayers=False):
            if id(p) in seen:
                continue
            seen.add(id(p))
            size = int(np.prod(p.shape))
            n_params += size
            if p.trainable:
                trainable += size
        total += n_params
        rows.append((name or type(sub).__name__, type(sub).__name__,
                     n_params))
    lines = [f"{'Layer':<40}{'Type':<28}{'Params':>12}", "-" * 80]
    for r in rows:
        lines.append(f"{r[0]:<40}{r[1]:<28}{r[2]:>12,}")
    lines += ["-" * 80, f"Total params: {total:,}",
              f"Trainable params: {trainable:,}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}

"""paddle.framework (reference python/paddle/framework/__init__.py):
re-export namespace for program/parameter/dtype/rng primitives."""
from ..tensor.compat import (  # noqa: F401
    create_global_var, create_parameter,
)
from ..static.param_attr import ParamAttr  # noqa: F401
from ..core.program import VarDesc as Variable  # noqa: F401
from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace,
)
from ..core.dtype import (  # noqa: F401
    get_default_dtype, set_default_dtype,
)
from ..core.generator import seed as manual_seed  # noqa: F401
from ..dygraph.engine import grad  # noqa: F401
from ..dygraph.layers import LayerList  # noqa: F401
from ..dygraph.base import no_grad  # noqa: F401
from ..dygraph.tensor import to_variable  # noqa: F401
from ..distributed.parallel import DataParallel  # noqa: F401
from ..io.framework_io import save, load  # noqa: F401
from ..optimizer.lr_scheduler import (  # noqa: F401
    NoamDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay,
)
from ..jit import SaveLoadConfig  # noqa: F401

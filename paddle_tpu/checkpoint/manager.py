"""CheckpointManager — async, atomic, preemption-safe training checkpoints.

The robustness tier the north-star workload needs: ERNIE-base pretraining
on a preemptible v5e slice must snapshot and resume without ever blocking
the train loop on disk or trusting a half-written directory.  Design in
the spirit of Orbax async checkpointing and Check-N-Run (NSDI '22):

* **snapshot / persist decoupling** — ``save()`` copies state to host
  (``jax.device_get`` + an owning copy: donated device buffers and
  in-place-mutated numpy arrays both invalidate zero-copy views)
  and returns; a single background writer thread does the slow part.  A
  bounded in-flight budget (``max_in_flight``) applies *backpressure*:
  when the writer falls behind, ``save()`` blocks instead of queueing
  unbounded host snapshots.
* **atomic commit with integrity** — shards + a JSON manifest (step,
  per-tensor shape/dtype/CRC-32, framework version) are staged in a
  temp dir, fsync'd, then renamed to ``step_<N>/`` (atomic.py).  A
  checkpoint directory under its final name is either complete or was
  never published.
* **verified load with fallback** — ``load()`` checks manifest shape/
  dtype/CRC per tensor and refuses truncated or bit-flipped shards,
  falling back to the previous valid step (``checkpoint.load_fallbacks``
  counter + a RuntimeWarning naming the corrupt dir).
* **retention** — keep-last-N ∪ keep-every-M-steps GC after each commit
  (generalizing incubate's ``clean_redundant_checkpoints``).
* **preemption** — ``install_preemption_handler()`` hooks SIGTERM/SIGINT:
  on signal the manager drains in-flight saves and writes one final
  synchronous checkpoint from the registered state provider before the
  process dies.

Monitor surface (core/monitor.py): ``checkpoint.save_seconds`` histogram,
``checkpoint.bytes_written`` / ``checkpoint.saves`` /
``checkpoint.save_failures`` / ``checkpoint.load_fallbacks`` counters,
``checkpoint.last_saved_step`` / ``checkpoint.in_flight`` gauges.

Multi-host layout (through the fleet FS abstraction): every host stages
``shard_<rank>.bin`` plus ``manifest_<rank>.json`` into a shared pending
dir; with ``world_size > 1`` nothing publishes inside ``save()`` — the
protocol is save-on-every-rank → ``wait()`` → cross-host barrier →
rank 0 ``commit(step)`` (atomic rename + GC), so a checkpoint can never
be published while another rank's shard is mid-write.  Each rank loads
strictly its own shard/manifest back, so per-host sharded params never
cross hosts.
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import signal
import threading
import time
import warnings
import zlib
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from ..core.serialization import decode_tensor, encode_tensor
from ..core.monitor import gauge_set, hist_observe, stat_add
from .atomic import (STAGE_SWEEP_GRACE_S, commit_dir, fsync_path,
                     new_temp_path, stage_idle_seconds, sweep_dead_stages)

__all__ = ["CheckpointManager", "Checkpoint", "CheckpointError",
           "FORMAT_VERSION"]

FORMAT_VERSION = "paddle_tpu.checkpoint/1"

_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointError(RuntimeError):
    pass


class Checkpoint(NamedTuple):
    """One verified restore: host arrays + the non-tensor sidecar."""
    step: int
    state: Dict[str, np.ndarray]
    extra: dict


class _Job(NamedTuple):
    step: int
    state: Dict[str, np.ndarray]
    extra: dict
    done: threading.Event


def _device_get(value) -> np.ndarray:
    """Host snapshot of one tensor — always BY VALUE.  jax arrays are
    immutable but their CPU buffers are not stable: device_get can alias
    the device buffer zero-copy, and the executor's donate_argnums step
    functions hand exactly those buffers back to XLA for reuse on the
    next train step, so an aliased view can be overwritten (or freed)
    while the async writer is still serializing it — and the shard CRC
    would validate the garbage.  numpy/.numpy() inputs are likewise
    copied: they can be mutated in place by the next step."""
    try:
        import jax
        if isinstance(value, jax.Array):
            a = np.asarray(jax.device_get(value))
            # copy only when device_get aliased the device buffer (CPU
            # backend); a TPU device_get already materialized an owning
            # host array and a second memcpy would double the train-side
            # snapshot cost for nothing
            return a if a.flags.owndata else a.copy()
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        pass
    if hasattr(value, "numpy"):
        return np.array(value.numpy())
    return np.array(value)


class CheckpointManager:
    """Numbered atomic checkpoints under ``root/step_<N>/``.

    Args:
        root: checkpoint directory (created on first save).
        keep_last_n: retention — always keep the newest N steps.
        keep_every_m_steps: additionally keep every step that is a
            multiple of M (0 disables; the long-horizon archive knob).
        max_in_flight: async save budget; ``save()`` blocks when this
            many snapshots are still being persisted (backpressure, not
            an unbounded queue).
        fs: fleet FS abstraction for discovery/GC (LocalFS default).
        rank / world_size: multi-host shard layout; only rank 0 writes
            the commit manifest and runs GC.
    """

    def __init__(self, root: str, keep_last_n: int = 5,
                 keep_every_m_steps: int = 0, max_in_flight: int = 1,
                 fs=None, rank: int = 0, world_size: int = 1):
        if keep_last_n < 1:
            raise ValueError("keep_last_n must be >= 1")
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        from ..distributed.fleet.utils.fs import LocalFS
        if fs is not None and not isinstance(fs, LocalFS):
            # shard writes/reads go through os/open on `root`; a remote FS
            # client would silently split state between local disk and the
            # remote listing — refuse loudly.  Remote stores are served by
            # a mounted path (GCS-fuse etc.) or incubate's CheckpointSaver.
            raise ValueError(
                "CheckpointManager requires a locally-mounted filesystem "
                f"(LocalFS), got {type(fs).__name__}; mount the store or "
                "use incubate.checkpoint.CheckpointSaver for remote FS "
                "clients")
        self.root = str(root)
        self.keep_last_n = int(keep_last_n)
        self.keep_every_m_steps = int(keep_every_m_steps)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self._fs = fs or LocalFS()
        self._slots = threading.BoundedSemaphore(int(max_in_flight))
        self._jobs: "queue.Queue[Optional[_Job]]" = queue.Queue()
        self._in_flight = 0
        # RLock: the preemption handler runs on a thread whose
        # interrupted frame may hold this lock (save()'s accounting)
        self._mu = threading.RLock()
        self._last_error: Optional[BaseException] = None
        self._state_provider: Optional[Callable[[], tuple]] = None
        self._prev_handlers: dict = {}
        self._closed = False
        if self.rank == 0:
            self._recover_pending()
            _cleanup_stale(self.root)
        self._writer = threading.Thread(target=self._writer_loop,
                                        daemon=True,
                                        name="paddle-tpu-ckpt-writer")
        self._writer.start()

    # -- naming -------------------------------------------------------------
    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step)}")

    def _shard_name(self, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return f"shard_{r:05d}.bin"

    def _manifest_name(self, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return "manifest.json" if r == 0 else f"manifest_{r:05d}.json"

    # -- save ---------------------------------------------------------------
    def save(self, step: int, state: Dict[str, object], extra: dict = None,
             sync: bool = False) -> int:
        """Snapshot `state` ({name: array}) at `step` and persist it.

        Returns immediately after the host snapshot unless `sync=True`
        or the in-flight budget is exhausted (then it blocks until a
        writer slot frees — backpressure instead of unbounded memory).
        Non-tensor training state (LR scheduler, RNG, dataset position)
        rides `extra`, which must be JSON-serializable."""
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        if not sync:
            # a sync save must NOT abort on a stale background failure:
            # it is the preemption path's last chance to persist state.
            # The stale error still surfaces at the next wait()/close().
            self._raise_pending_error()
        host = {name: _device_get(v) for name, v in state.items()
                if v is not None}
        if not host:
            # a zero-tensor checkpoint commits clean (valid manifest, no
            # CRC to fail) and restores nothing — almost always a caller
            # bug (snapshot taken from the wrong scope)
            warnings.warn(
                f"checkpoint save(step={step}) got an EMPTY state dict; "
                "committing a checkpoint that restores no tensors",
                RuntimeWarning, stacklevel=2)
        job = _Job(int(step), host, dict(extra or {}), threading.Event())
        if sync:
            t0 = time.monotonic()
            self._persist(job)
            self._note_saved(job.step, time.monotonic() - t0)
            return job.step
        t0 = time.monotonic()
        self._slots.acquire()  # backpressure point
        waited = time.monotonic() - t0
        if waited > 1e-4:
            hist_observe("checkpoint.backpressure_seconds", waited)
        with self._mu:
            self._in_flight += 1
            gauge_set("checkpoint.in_flight", self._in_flight)
        self._jobs.put(job)
        return job.step

    def _writer_loop(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            t0 = time.monotonic()
            try:
                self._persist(job)
                self._note_saved(job.step, time.monotonic() - t0)
            except BaseException as e:  # noqa: BLE001 - surfaced at caller
                self._last_error = e
                stat_add("checkpoint.save_failures")
            finally:
                job.done.set()
                with self._mu:
                    self._in_flight -= 1
                    gauge_set("checkpoint.in_flight", self._in_flight)
                self._slots.release()
                self._jobs.task_done()

    def _persist(self, job: _Job) -> None:
        """The slow half: stage shards + manifest, fsync, atomic rename,
        then retention GC.  Runs on the writer thread (async) or the
        caller (sync / final preemption save)."""
        os.makedirs(self.root, exist_ok=True)
        final = self.step_dir(job.step)
        if self.world_size > 1:
            # shared staging dir so every rank lands in the same commit
            stage = os.path.join(self.root, f".pending.step_{job.step}")
            os.makedirs(stage, exist_ok=True)
        else:
            stage = new_temp_path(final)
            os.makedirs(stage)
        tensors = {}
        nbytes = 0
        shard_path = os.path.join(stage, self._shard_name())
        with open(shard_path, "wb") as f:
            for name in sorted(job.state):
                view, tag = encode_tensor(job.state[name])
                buf = view.tobytes()
                tensors[name] = {
                    "shape": list(np.shape(job.state[name])),
                    "dtype": tag,
                    "vdtype": view.dtype.str,
                    "shard": self._shard_name(),
                    "offset": nbytes,
                    "nbytes": len(buf),
                    "crc32": zlib.crc32(buf),
                }
                f.write(buf)
                nbytes += len(buf)
            f.flush()
            os.fsync(f.fileno())
        # fault injection (paddle_tpu/testing/chaos.py): the window
        # between shard bytes and manifest/commit is exactly where a
        # preempted host tears a checkpoint — chaos makes that timing
        # reproducible (slow_save / torn_save)
        from ..testing import chaos as _chaos
        _chaos.save_hook(stage, job.step)
        manifest = {
            "format": FORMAT_VERSION,
            "framework_version": _framework_version(),
            "step": job.step,
            "rank": self.rank,
            "world_size": self.world_size,
            "tensors": tensors,
            "extra": job.extra,
        }
        man_path = os.path.join(stage, self._manifest_name())
        with open(man_path, "w") as f:
            json.dump(manifest, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        stat_add("checkpoint.bytes_written", nbytes)
        from ..observability.journal import emit as _jemit
        _jemit("checkpoint_save", step=int(job.step), bytes=int(nbytes))
        if self.world_size == 1:
            # manifest.json is the commit marker; the rename publishes it
            commit_dir(stage, final, fsync=False)  # files fsync'd above
            fsync_path(self.root)
            self._gc()
            _jemit("checkpoint_commit", step=int(job.step), path=final)
        # world_size > 1: every rank only STAGES here.  Publishing is a
        # separate step — the caller barriers across hosts, then rank 0
        # calls commit(step).  Committing inside save() would let rank 0
        # publish (and GC) a checkpoint whose other-rank shards are still
        # being written.
        if self.world_size > 1 and self.rank == 0:
            # no-barrier mode never commits during the run: without
            # pruning, a long run accumulates one full model copy per
            # save under .pending.*
            self._prune_stale_pending()

    def commit(self, step: int) -> None:
        """Publish a multi-host staged checkpoint (rank 0 only; no-op on
        other ranks).  Call AFTER save(step) has returned on every rank
        AND a cross-host barrier::

            mgr.save(step, state)      # all ranks
            mgr.wait()                 # all ranks: shard staged + fsync'd
            barrier()                  # e.g. collective.barrier()
            mgr.commit(step)           # rank 0: atomic publish + GC

        Single-host managers (world_size == 1) commit inside save() and
        never need this."""
        if self.rank != 0 or self.world_size == 1:
            return
        stage = os.path.join(self.root, f".pending.step_{int(step)}")
        if not os.path.isdir(stage):
            raise CheckpointError(
                f"no staged checkpoint for step {step} at {stage} — "
                "call save() on every rank first")
        commit_dir(stage, self.step_dir(step))
        fsync_path(self.root)
        self._gc()
        from ..observability.journal import emit as _jemit
        _jemit("checkpoint_commit", step=int(step),
               path=self.step_dir(step))

    def _note_saved(self, step: int, seconds: float) -> None:
        stat_add("checkpoint.saves")
        hist_observe("checkpoint.save_seconds", seconds)
        gauge_set("checkpoint.last_saved_step", step)

    def wait(self) -> None:
        """Drain every queued/in-flight save; re-raises the first writer
        error, if any."""
        self._jobs.join()
        self._raise_pending_error()

    def _raise_pending_error(self):
        if self._last_error is not None:
            err, self._last_error = self._last_error, None
            raise CheckpointError(
                f"background checkpoint save failed: {err!r}") from err

    # -- discovery ----------------------------------------------------------
    def all_steps(self) -> List[int]:
        """Committed step numbers (dirs with a rank-0 manifest), ascending.
        No shard verification — see latest_step()/load() for validity."""
        if not self._fs.is_exist(self.root):
            return []
        dirs, _files = self._fs.ls_dir(self.root)
        steps = []
        for d in dirs:
            m = _STEP_RE.match(d)
            if m and os.path.exists(os.path.join(
                    self.root, d, "manifest.json")):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        """Newest step that passes the cheap validity screen (manifest
        parses; every declared shard byte range exists on disk).  A
        truncated shard — the half-written artifact a preemption leaves
        when atomicity is violated out-of-band — is skipped here; CRC
        verification happens at load()."""
        for step in reversed(self.all_steps()):
            if self._screen(step) is not None:
                return step
        return None

    def _screen(self, step: int) -> Optional[dict]:
        """Parse + size-check one step's manifest; None if invalid.

        Strictly THIS rank's manifest: falling back to rank 0's would
        silently restore rank-0's parameter shard as this host's state —
        a missing rank manifest makes the step invalid here instead.
        (One screen implementation for load() and load_merged():
        delegates to `_rank_manifest`.)"""
        return self._rank_manifest(step, self.rank)

    # -- load ---------------------------------------------------------------
    def load(self, step: Optional[int] = None,
             on_mismatch: str = "convert") -> Optional[Checkpoint]:
        """Restore the newest valid checkpoint (or exactly `step`).

        Every tensor is CRC-verified against the manifest; a corrupt or
        truncated checkpoint is never returned — with `step=None` the
        manager warns and falls back to the previous valid step, with an
        explicit `step` it raises CheckpointError.

        ``on_mismatch`` governs a WORLD-SIZE mismatch at the storage
        layer (the checkpoint was written by a different rank count —
        the cross-host elastic re-form path, docs/elastic.md):

          * ``"convert"`` (default) routes the step through
            `load_merged`: every writer rank's shard manifest is read
            and reassembled into one rank-complete state;
          * ``"error"`` raises `CheckpointError` naming both worlds;
          * ``"warn"`` restores the old behaviour — read only THIS
            rank's shard and warn that vanished ranks' state is lost.
        """
        if on_mismatch not in ("convert", "error", "warn"):
            raise ValueError(
                f"on_mismatch must be 'convert', 'error' or 'warn', "
                f"got {on_mismatch!r}")
        if step is not None:
            manifest = self._screen(step)
            if manifest is None:
                if on_mismatch == "convert" and \
                        self._foreign_world(step) is not None:
                    # a GROWN world: this rank has no shard of its own
                    # in the old layout, but the merged state serves it
                    return self.load_merged(step=step)
                raise CheckpointError(
                    f"checkpoint {self.step_dir(step)} is missing, "
                    "incomplete, or truncated")
            return self._read(step, manifest, on_mismatch=on_mismatch)
        for cand in reversed(self.all_steps()):
            manifest = self._screen(cand)
            if manifest is None:
                if on_mismatch == "convert" and \
                        self._foreign_world(cand) is not None:
                    try:
                        return self.load_merged(step=cand)
                    except CheckpointError as e:
                        self._fallback_warn(cand, str(e))
                        continue
                self._fallback_warn(cand, "incomplete or truncated")
                continue
            try:
                return self._read(cand, manifest, on_mismatch=on_mismatch)
            except CheckpointError as e:
                self._fallback_warn(cand, str(e))
        return None

    def _foreign_world(self, step: int) -> Optional[int]:
        """The step's writer world size when it DIFFERS from this
        manager's (screened via the rank-0 manifest), else None."""
        man0 = self._rank_manifest(step, 0)
        if man0 is None:
            return None
        saved = int(man0.get("world_size", 1))
        return saved if saved != self.world_size else None

    def _fallback_warn(self, step: int, why: str) -> None:
        stat_add("checkpoint.load_fallbacks")
        warnings.warn(
            f"checkpoint {self.step_dir(step)} refused ({why}); "
            "falling back to the previous valid step", RuntimeWarning,
            stacklevel=3)

    def _read(self, step: int, manifest: dict,
              on_mismatch: str = "convert") -> Checkpoint:
        saved_world = int(manifest.get("world_size", 1))
        if saved_world != self.world_size:
            # topology shift at the storage layer: this manager's rank
            # layout differs from the writer's
            if on_mismatch == "convert":
                return self.load_merged(step=step)
            if on_mismatch == "error":
                raise CheckpointError(
                    f"checkpoint step {step} was written by a world of "
                    f"{saved_world} ranks but is being loaded by a "
                    f"world of {self.world_size} ranks "
                    f"(on_mismatch='error'; pass on_mismatch='convert' "
                    f"for the rank-merged restore, docs/elastic.md)")
            warnings.warn(
                f"checkpoint step {step} was written by a world of "
                f"{saved_world} ranks but is being loaded by a world of "
                f"{self.world_size}; rank-private shards of vanished "
                "ranks are NOT merged under on_mismatch='warn' — pass "
                "on_mismatch='convert' (or call load_merged) for the "
                "rank-merged restore (docs/elastic.md)",
                RuntimeWarning, stacklevel=3)
        state = self._read_state(step, manifest)
        return Checkpoint(step=int(manifest["step"]), state=state,
                          extra=dict(manifest.get("extra", {})))

    def _read_state(self, step: int, manifest: dict) \
            -> Dict[str, np.ndarray]:
        """CRC-verified tensor read of one rank's manifest+shard."""
        state: Dict[str, np.ndarray] = {}
        by_shard: Dict[str, List[tuple]] = {}
        for name, meta in manifest["tensors"].items():
            by_shard.setdefault(meta["shard"], []).append((name, meta))
        for shard, entries in by_shard.items():
            path = os.path.join(self.step_dir(step), shard)
            with open(path, "rb") as f:
                for name, meta in sorted(entries,
                                         key=lambda e: e[1]["offset"]):
                    f.seek(meta["offset"])
                    buf = f.read(meta["nbytes"])
                    if len(buf) != meta["nbytes"]:
                        raise CheckpointError(
                            f"shard {shard} truncated at {name!r}")
                    if zlib.crc32(buf) != meta["crc32"]:
                        raise CheckpointError(
                            f"CRC mismatch for {name!r} in {shard}")
                    # .copy(): the restored array must OWN its memory.
                    # A bytes-backed frombuffer view can be zero-copy
                    # aliased by jnp.asarray downstream, and the
                    # executor's donate_argnums step would then free
                    # memory XLA doesn't own (heap corruption).
                    view = np.frombuffer(
                        buf, dtype=np.dtype(meta["vdtype"])).copy()
                    state[name] = decode_tensor(
                        view.reshape(meta["shape"]), meta["dtype"])
        return state

    # -- rank-merged load (cross-host world change, docs/elastic.md) --------
    def _rank_manifest(self, step: int, rank: int) -> Optional[dict]:
        """Parse + size-screen an EXPLICIT rank's manifest of `step`
        (same validity screen as `_screen`, which covers only this
        manager's own rank)."""
        path = os.path.join(self.step_dir(step), self._manifest_name(rank))
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        if manifest.get("format") != FORMAT_VERSION:
            return None
        sizes: Dict[str, int] = {}
        for meta in manifest.get("tensors", {}).values():
            shard = os.path.join(self.step_dir(step), meta["shard"])
            if shard not in sizes:
                try:
                    sizes[shard] = os.path.getsize(shard)
                except OSError:
                    return None
            if meta["offset"] + meta["nbytes"] > sizes[shard]:
                return None
        return manifest

    def load_merged(self, step: Optional[int] = None,
                    world: Optional[int] = None) -> Optional[Checkpoint]:
        """Rank-merged restore: read EVERY writer rank's shard manifest
        of `step` (the writer world comes from the rank-0 manifest) and
        reassemble one rank-complete global state — the load path for a
        host-count change, where this manager's rank layout no longer
        matches the checkpoint's (fleet re-form, docs/elastic.md).

        Merge rules, per tensor name:

          * present in ONE rank — rank-private state, carried through;
          * present in SEVERAL ranks, bitwise identical — replicated
            state (the elastic fold guarantees per-host state is
            rank-complete and identical), one copy carried;
          * present in several ranks and DIFFERING — the hosts diverged;
            refused with `CheckpointError` (silently picking one would
            launder corruption into a resume).

        ``world``: the dp degree of the world that will CONSUME the
        merged state.  When the checkpoint records a ZeRO
        ``zero_shard_plan`` whose dp_degree differs, the bucketed
        layout is routed through ``sharding.unshard_state`` to the
        plain per-param layout (bucket padding is world-dependent, so
        the old bucket arrays cannot be re-fed directly) and the plan
        is dropped from the sidecar; `Executor.restore_from_checkpoint`
        then re-shards for the target program's own plan
        (``reshard_state``) — the unshard→reshard conversion pair that
        already carries single-host shard-count changes.

        With ``step=None`` walks committed steps newest-first and falls
        back past unmergeable ones, like `load`."""
        if step is None:
            for cand in reversed(self.all_steps()):
                try:
                    return self.load_merged(step=cand, world=world)
                except CheckpointError as e:
                    self._fallback_warn(cand, str(e))
            return None
        man0 = self._rank_manifest(step, 0)
        if man0 is None:
            raise CheckpointError(
                f"checkpoint {self.step_dir(step)} has no valid rank-0 "
                "manifest — nothing to merge")
        saved_world = int(man0.get("world_size", 1))
        state: Dict[str, np.ndarray] = {}
        owner: Dict[str, int] = {}
        conflicts: List[str] = []
        for rank in range(saved_world):
            man = man0 if rank == 0 else self._rank_manifest(step, rank)
            if man is None:
                raise CheckpointError(
                    f"rank-merged load of step {step}: rank {rank} of "
                    f"the writing world ({saved_world}) has a missing "
                    "or truncated manifest/shard")
            for name, arr in self._read_state(step, man).items():
                prev = state.get(name)
                if prev is None:
                    state[name] = arr
                    owner[name] = rank
                elif prev.shape != arr.shape or prev.dtype != arr.dtype \
                        or not np.array_equal(prev, arr):
                    conflicts.append(
                        f"{name!r} (rank {owner[name]} vs rank {rank})")
        if conflicts:
            raise CheckpointError(
                f"rank-merged load of step {step}: {len(conflicts)} "
                f"tensor(s) differ between writer ranks — the hosts "
                f"diverged and no merge is sound: "
                f"{conflicts[:6]}{'...' if len(conflicts) > 6 else ''}")
        extra = dict(man0.get("extra", {}))
        extra["merged_from_world"] = saved_world
        plan = extra.get("zero_shard_plan")
        if plan and world and int(world) != int(plan.get("dp_degree", 1)):
            # bucket padding is a function of the dp degree, so the old
            # world's bucket arrays cannot feed the new world's program;
            # unshard to the plain per-param layout here and let the
            # executor's topology-shift conversion re-shard for the
            # target program's own recorded plan
            from ..distributed.sharding import unshard_state
            state = unshard_state(state, plan)
            extra.pop("zero_shard_plan", None)
            extra.pop("dp_degree", None)
            warnings.warn(
                f"rank-merged load: ZeRO layout recorded for dp="
                f"{plan.get('dp_degree')} unsharded to the plain layout "
                f"for the new world of {world} (restore re-shards "
                "against the target program's plan)", RuntimeWarning,
                stacklevel=2)
        stat_add("checkpoint.merged_loads")
        from ..observability.journal import emit as _jemit
        _jemit("restore_merged", step=int(man0["step"]),
               merged_from_world=saved_world, world=self.world_size)
        return Checkpoint(step=int(man0["step"]), state=state,
                          extra=extra)

    # -- multi-host pending recovery ----------------------------------------
    def _prune_stale_pending(self) -> None:
        """Bound .pending.* growth in no-barrier multi-host mode (rank 0).

        Keeps every stage at or newer than the newest RECOVERABLE point —
        the newest committed step or fully-staged pending (what the next
        startup's _recover_pending would publish) — and sweeps older
        stages only once idle past the cross-host grace window, so a
        slow rank's in-progress stage is never deleted under it."""
        pending = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            m = re.match(r"^\.pending\.step_(\d+)$", name)
            if m:
                pending.append((int(m.group(1)),
                                os.path.join(self.root, name)))
        if not pending:
            return
        committed = self.all_steps()
        newest_safe = max([s for s, p in pending
                           if self._pending_complete(p)] +
                          committed, default=None)
        if newest_safe is None:
            return
        for step, path in pending:
            if step >= newest_safe:
                continue
            if stage_idle_seconds(path) < STAGE_SWEEP_GRACE_S:
                continue  # possibly a slow rank still writing
            shutil.rmtree(path, ignore_errors=True)
            stat_add("checkpoint.pending_pruned")

    def _recover_pending(self) -> None:
        """Commit (or drop) `.pending.step_<N>` stages left by a previous
        process.  A multi-host preemption save can only STAGE inside the
        dying signal handler — the cross-host barrier + rank-0 commit()
        can never run there — so on the next startup rank 0 publishes any
        stage whose every rank finished writing (all manifests present,
        shard byte ranges intact) and deletes the rest.  This is what
        makes the SIGTERM final save real on world_size > 1."""
        if not os.path.isdir(self.root):
            return
        for name in os.listdir(self.root):
            m = re.match(r"^\.pending\.step_(\d+)$", name)
            if m is None:
                continue
            stage = os.path.join(self.root, name)
            step = int(m.group(1))
            if self._pending_complete(stage):
                commit_dir(stage, self.step_dir(step))
                fsync_path(self.root)
                stat_add("checkpoint.pending_recovered")
            else:
                shutil.rmtree(stage, ignore_errors=True)

    @staticmethod
    def _pending_complete(stage: str) -> bool:
        """Every rank the rank-0 manifest declares has a parseable
        manifest whose shard byte ranges exist in the stage dir."""
        def _manifest(rank: int) -> Optional[dict]:
            name = "manifest.json" if rank == 0 \
                else f"manifest_{rank:05d}.json"
            try:
                with open(os.path.join(stage, name)) as f:
                    man = json.load(f)
            except (OSError, ValueError):
                return None
            return man if man.get("format") == FORMAT_VERSION else None

        root_man = _manifest(0)
        if root_man is None:
            return False
        for rank in range(int(root_man.get("world_size", 1))):
            man = _manifest(rank) if rank else root_man
            if man is None:
                return False
            for meta in man.get("tensors", {}).values():
                path = os.path.join(stage, meta["shard"])
                try:
                    size = os.path.getsize(path)
                except OSError:
                    return False
                if meta["offset"] + meta["nbytes"] > size:
                    return False
        return True

    # -- retention ----------------------------------------------------------
    def _gc(self) -> None:
        """keep-last-N ∪ keep-every-M retention over committed steps."""
        steps = self.all_steps()
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_m_steps > 0:
            keep.update(s for s in steps
                        if s % self.keep_every_m_steps == 0)
        for s in steps:
            if s not in keep:
                self._fs.delete(self.step_dir(s))
                stat_add("checkpoint.gc_deleted")

    # -- preemption ---------------------------------------------------------
    def set_state_provider(self, fn: Callable[[], tuple]) -> None:
        """Register a zero-arg callable returning (step, state, extra) —
        the live training state the final preemption save snapshots."""
        self._state_provider = fn

    def preemption_save(self, drain_timeout: float = 60.0) -> Optional[int]:
        """Drain in-flight saves (bounded), then write one final
        SYNCHRONOUS checkpoint from the state provider.  Returns the
        saved step (None when no provider is registered).  Called from
        the signal handler; safe to call directly (orderly shutdown).

        Signal-context discipline: the drain POLLS the queue's
        unfinished count instead of Queue.join() — the handler runs on
        the thread whose interrupted frame may hold the queue's internal
        lock, and join() there would self-deadlock.  The drain is also
        time-bounded: if the writer can't finish in `drain_timeout`
        seconds, the final sync save (which bypasses the queue entirely)
        still goes out — a newer checkpoint beats a drained queue."""
        deadline = time.monotonic() + drain_timeout
        while self._jobs.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.05)
        if self._state_provider is None:
            return None
        provided = self._state_provider()
        if provided is None:
            # provider registered but nothing to save yet (e.g. hapi fit
            # preempted before its first epoch completed)
            return None
        step, state, extra = provided
        stat_add("checkpoint.preemption_saves")
        return self.save(step, state, extra=extra, sync=True)

    def install_preemption_handler(self,
                                   signals=(signal.SIGTERM, signal.SIGINT)):
        """SIGTERM/SIGINT → drain + final synchronous checkpoint, then the
        previous disposition runs (so Ctrl-C still interrupts and the
        platform's kill still kills — just after the state is safe).
        Idempotent: a second install never records the handler as its own
        predecessor (which would recurse on signal)."""
        for sig in signals:
            prev = signal.signal(sig, self._handle_preemption)
            # == not `is`: bound methods are re-created on each access
            if prev != self._handle_preemption:
                self._prev_handlers[sig] = prev

    def uninstall_preemption_handler(self):
        for sig, prev in self._prev_handlers.items():
            signal.signal(sig, prev)
        self._prev_handlers.clear()

    def _handle_preemption(self, signum, frame):
        try:
            self.preemption_save()
        finally:
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_IGN:
                pass  # previously ignored stays ignored (post-save)
            elif signum == signal.SIGINT:
                raise KeyboardInterrupt
            else:
                raise SystemExit(128 + signum)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        """Drain pending saves and stop the writer thread.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._jobs.join()
        self._jobs.put(None)
        self._writer.join(timeout=30.0)
        self.uninstall_preemption_handler()
        self._raise_pending_error()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _framework_version() -> str:
    try:
        import paddle_tpu
        return getattr(paddle_tpu, "__version__", "0")
    except ImportError:  # pragma: no cover
        return "0"


def _cleanup_stale(root: str) -> None:
    """Remove abandoned staging dirs from a previous crashed process.

    `.stale.<base>.<pid>.<hex>` dirs are special: commit_dir moves a
    same-name checkpoint aside under that name while re-publishing, so a
    crash between its two renames leaves the stale copy as the ONLY
    complete version of that step — recover it back to `<base>` instead
    of deleting it (unless the re-publish completed and `<base>`
    exists)."""
    if not os.path.isdir(root):
        return
    for name in os.listdir(root):
        path = os.path.join(root, name)
        if name.startswith(".stale."):
            base = name[len(".stale."):].rsplit(".", 2)[0]
            final = os.path.join(root, base)
            if not os.path.exists(final):
                try:
                    os.rename(path, final)
                    continue
                except OSError:
                    pass
            shutil.rmtree(path, ignore_errors=True)
    # .pending.* is owned by _recover_pending (commit-or-drop); .tmp.*
    # stages are swept only when their owner is dead AND they have gone
    # idle (a live concurrent manager on this root — e.g. an eval job
    # starting while training's writer is mid-_persist — keeps its stage)
    sweep_dead_stages(root, ".tmp.")

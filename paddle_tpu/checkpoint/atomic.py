"""Atomic filesystem commit primitives for the checkpoint tier.

Crash-safety contract: a reader never observes a partially-written
artifact under its final name.  Files are written to a same-directory
temp name, fsync'd, then `os.replace`d into place (POSIX rename
atomicity); directories are staged under a dot-prefixed temp name and
renamed as a unit, with the parent directory fsync'd so the rename
itself survives a power cut.  Check-N-Run (NSDI '22) calls this the
decoupling point between *snapshot* (cheap, in-memory) and *persist*
(slow, crash-exposed); everything here is the persist half.
"""
from __future__ import annotations

import contextlib
import os
import uuid
import zlib

__all__ = ["atomic_write", "fsync_path", "fsync_dir", "commit_dir",
           "new_temp_path", "crc32_file", "stage_idle_seconds",
           "sweep_dead_stages", "STAGE_SWEEP_GRACE_S"]

# how long an abandoned-looking stage dir must sit unmodified before a
# sweep may delete it: the pid-liveness test in stage names is HOST-local,
# so on a shared mount another host's live writer looks dead — but it
# never goes this long without writing
STAGE_SWEEP_GRACE_S = 3600.0


def stage_idle_seconds(stage: str) -> float:
    """Seconds since anything under `stage` was last modified."""
    import time
    newest = 0.0
    for root, _dirs, files in os.walk(stage):
        for entry in [root] + files:
            p = entry if entry == root else os.path.join(root, entry)
            try:
                newest = max(newest, os.path.getmtime(p))
            except OSError:
                pass
    return time.time() - newest


def sweep_dead_stages(parent: str, prefix: str = ".tmp.") -> None:
    """Remove staging dirs under `parent` abandoned by a crashed writer.

    Stage names embed the writer's pid (new_temp_path); a stage whose
    owner is still alive belongs to a concurrent in-progress save and is
    kept.  The pid test is HOST-local — on a shared mount another host's
    live writer looks dead here — so a dead-looking stage is only swept
    once it has also been idle past STAGE_SWEEP_GRACE_S, longer than any
    in-progress save ever goes without writing."""
    import shutil
    if not os.path.isdir(parent):
        return
    for name in os.listdir(parent):
        if not name.startswith(prefix):
            continue
        path = os.path.join(parent, name)
        if not os.path.isdir(path):
            continue
        try:
            pid = int(name.rsplit(".", 2)[-2])
            os.kill(pid, 0)  # raises if no such process
            continue  # owner alive: in-progress stage, keep
        except (ValueError, IndexError, ProcessLookupError):
            pass  # unparseable or owner dead (on THIS host)
        except PermissionError:
            continue  # pid exists under another uid: keep
        if stage_idle_seconds(path) < STAGE_SWEEP_GRACE_S:
            continue  # possibly another host's live writer
        shutil.rmtree(path, ignore_errors=True)


def fsync_path(path: str) -> None:
    """fsync one file by path (no-op if the OS refuses, e.g. some network
    mounts return EINVAL — the rename still orders after the writes)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Persist directory entries (created/renamed names) themselves."""
    fsync_path(path)


def new_temp_path(final_path: str, prefix: str = ".tmp.") -> str:
    """A unique same-directory temp name for `final_path` (same dir =>
    os.replace is a rename, never a copy)."""
    d, base = os.path.split(final_path)
    return os.path.join(d, f"{prefix}{base}.{os.getpid()}.{uuid.uuid4().hex[:8]}")


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb", fsync: bool = True):
    """Write-temp-then-rename for a single file::

        with atomic_write(prefix + ".pdparams") as f:
            pickle.dump(state, f)

    On success the temp file is fsync'd and renamed over `path`; on any
    exception the temp is removed and `path` is untouched — a crash
    mid-write can never corrupt an existing artifact."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = new_temp_path(path)
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        if fsync:
            try:
                os.fsync(f.fileno())
            except OSError:
                pass
        f.close()
        os.replace(tmp, path)
        if fsync and d:
            fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            f.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def commit_dir(tmp_dir: str, final_dir: str, fsync: bool = True) -> None:
    """Atomically publish a fully-written staging directory.

    fsyncs every file in `tmp_dir` (unless already done by the writer),
    renames it to `final_dir` (replacing a stale same-name dir), and
    fsyncs the parent so the commit is durable.  After this returns,
    `final_dir` either exists complete or the rename never happened."""
    if fsync:
        for root, _dirs, files in os.walk(tmp_dir):
            for name in files:
                fsync_path(os.path.join(root, name))
        fsync_dir(tmp_dir)
    if os.path.isdir(final_dir):
        # a re-save of the same step: move the old dir aside first so the
        # rename below is a plain atomic publish, then drop the old one
        import shutil
        stale = new_temp_path(final_dir, prefix=".stale.")
        os.rename(final_dir, stale)
        os.rename(tmp_dir, final_dir)
        shutil.rmtree(stale, ignore_errors=True)
    else:
        os.rename(tmp_dir, final_dir)
    parent = os.path.dirname(final_dir)
    if fsync and parent:
        fsync_dir(parent)


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    """Streaming CRC-32 of a file (integrity line in checkpoint meta)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)

"""paddle_tpu.checkpoint — async atomic checkpointing + auto-resume.

The preemption-safety tier for TPU-pod training (docs/checkpoint.md):

* ``CheckpointManager`` (manager.py) — async background persistence with
  a bounded in-flight budget, atomic temp-dir-then-rename commits, CRC
  manifests, keep-last-N / keep-every-M retention, ``latest_step()``
  discovery that skips truncated checkpoints, and SIGTERM/SIGINT final
  saves.
* atomic primitives (atomic.py) — ``atomic_write`` (write-temp-rename
  for single files, used by hapi ``Model.save`` and ``paddle.save``),
  ``commit_dir``, fsync helpers.

Integration points: ``Executor.enable_checkpointing`` /
``Executor.restore_from_checkpoint`` (static), ``Model.fit(...,
resume=True)`` (hapi), and ``incubate.checkpoint.CheckpointSaver``
(fluid-parity surface re-based on the same atomic commit protocol).
"""
from .atomic import atomic_write, commit_dir, crc32_file, fsync_dir  # noqa: F401
from .manager import (  # noqa: F401
    Checkpoint, CheckpointError, CheckpointManager, FORMAT_VERSION,
)

__all__ = [
    "CheckpointManager", "Checkpoint", "CheckpointError", "FORMAT_VERSION",
    "atomic_write", "commit_dir", "crc32_file", "fsync_dir",
]

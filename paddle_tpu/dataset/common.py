"""paddle.dataset.common (reference python/paddle/dataset/common.py):
DATA_HOME, md5, download (local-only here), reader file splitting."""
from __future__ import annotations

import glob
import hashlib
import os
import pickle

from ..vision.datasets import DATA_HOME  # same cache layout

__all__ = ["DATA_HOME", "md5file", "download", "split",
           "cluster_files_reader"]


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Zero-egress environment: resolves to the cached local path and
    verifies the checksum; raises with instructions when absent instead
    of fetching (reference common.py:62 downloads)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if not os.path.exists(filename):
        raise FileNotFoundError(
            f"{filename} not present and this environment has no network "
            f"access — place the archive from {url} there manually")
    if md5sum and md5file(filename) != md5sum:
        raise IOError(f"{filename} md5 mismatch (expected {md5sum})")
    return filename


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Split a reader's samples into pickled chunk files of line_count
    samples each (reference common.py:126)."""
    if not callable(reader):
        raise TypeError("reader must be callable")
    if "%" not in suffix:
        raise ValueError("suffix must contain %d-style placeholder")
    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    lines = []
    index = 0
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % index, "wb") as f:
                dumper(lines, f)
            lines = []
            index += 1
    if lines:
        with open(suffix % index, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over this trainer's shard of pickled chunk files
    (reference common.py:157)."""
    loader = loader or (lambda f: pickle.load(f))

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [f for i, f in enumerate(file_list)
                    if i % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, "rb") as f:
                for line in loader(f):
                    yield line

    return reader

"""paddle.dataset.mnist readers (reference python/paddle/dataset/
mnist.py): samples are (784 float32 pixels scaled to [-1, 1], int
label)."""
from __future__ import annotations

import numpy as np

from ..vision.datasets import MNIST

__all__ = ["train", "test"]


def _reader_creator(mode):
    def reader():
        ds = MNIST(mode=mode)
        images = ds.images.reshape(len(ds), -1).astype(np.float32)
        images = images / 255.0 * 2.0 - 1.0
        for img, label in zip(images, ds.labels):
            yield img, int(label)

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")

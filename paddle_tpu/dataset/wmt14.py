"""paddle.dataset.wmt14 readers (reference python/paddle/dataset/
wmt14.py)."""
from __future__ import annotations

import os

from .common import DATA_HOME
from ..text.datasets import WMT14 as _WMT14

__all__ = ["train", "test", "gen", "get_dict"]


def _path(data_file):
    return data_file or os.path.join(DATA_HOME, "wmt14", "wmt14.tgz")


def _reader_creator(mode, dict_size, data_file=None):
    def reader():
        ds = _WMT14(_path(data_file), mode=mode, dict_size=dict_size)
        for i in range(len(ds)):
            src, trg, nxt = ds.src_ids[i], ds.trg_ids[i], \
                ds.trg_ids_next[i]
            yield src, trg, nxt

    return reader


def train(dict_size, data_file=None):
    return _reader_creator("train", dict_size, data_file)


def test(dict_size, data_file=None):
    return _reader_creator("test", dict_size, data_file)


def gen(dict_size, data_file=None):
    return _reader_creator("gen", dict_size, data_file)


def get_dict(dict_size, reverse=True, data_file=None):
    ds = _WMT14(_path(data_file), mode="train", dict_size=dict_size)
    return ds.get_dict(reverse=reverse)

"""paddle.dataset.conll05 readers (reference python/paddle/dataset/
conll05.py): SRL test reader + dicts + pretrained embedding loader."""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME
from ..text.datasets import Conll05st as _Conll05st

__all__ = ["test", "get_dict", "get_embedding"]


def _dataset(data_file=None, word_dict_file=None, verb_dict_file=None,
             target_dict_file=None):
    return _Conll05st(data_file, word_dict_file, verb_dict_file,
                      target_dict_file)


def test(data_file=None, word_dict_file=None, verb_dict_file=None,
         target_dict_file=None):
    def reader():
        ds = _dataset(data_file, word_dict_file, verb_dict_file,
                      target_dict_file)
        for i in range(len(ds)):
            yield ds[i]

    return reader


def get_dict(data_file=None, word_dict_file=None, verb_dict_file=None,
             target_dict_file=None):
    ds = _dataset(data_file, word_dict_file, verb_dict_file,
                  target_dict_file)
    return ds.get_dict()


def get_embedding(emb_file=None):
    """Load the pretrained word-embedding table (one vector per line)."""
    emb_file = emb_file or os.path.join(DATA_HOME, "conll05st",
                                        "emb.txt")
    if not os.path.exists(emb_file):
        raise FileNotFoundError(
            f"{emb_file} not found (zero-egress environment)")
    return np.loadtxt(emb_file, dtype=np.float32)

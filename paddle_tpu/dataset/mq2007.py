"""paddle.dataset.mq2007 readers (reference python/paddle/dataset/
mq2007.py): LETOR 4.0 learning-to-rank lines
`<rel> qid:<q> 1:<f1> 2:<f2> ... #docid = ...` grouped per query;
pointwise / pairwise / listwise sample formats."""
from __future__ import annotations

import itertools
import os

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test"]


def _parse(path):
    """-> {qid: [(rel, feature_vector), ...]} preserving file order."""
    queries = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            assert parts[1].startswith("qid:"), parts[1]
            qid = parts[1][4:]
            feats = [float(p.split(":")[1]) for p in parts[2:]]
            queries.setdefault(qid, []).append(
                (rel, np.asarray(feats, np.float32)))
    return queries


def _reader_creator(path, fmt):
    def reader():
        queries = _parse(path)
        for qid, docs in queries.items():
            if fmt == "pointwise":
                for rel, vec in docs:
                    yield vec, rel
            elif fmt == "pairwise":
                for (r1, v1), (r2, v2) in itertools.combinations(docs, 2):
                    if r1 == r2:
                        continue
                    if r1 > r2:
                        yield 1, v1, v2
                    else:
                        yield 1, v2, v1
            elif fmt == "listwise":
                yield [r for r, _ in docs], [v for _, v in docs]
            else:
                raise ValueError(f"unknown format {fmt!r}")

    return reader


def _path(split, data_file):
    return data_file or os.path.join(DATA_HOME, "MQ2007", "MQ2007",
                                     "Fold1", f"{split}.txt")


def train(format="pairwise", data_file=None):
    return _reader_creator(_path("train", data_file), format)


def test(format="pairwise", data_file=None):
    return _reader_creator(_path("test", data_file), format)

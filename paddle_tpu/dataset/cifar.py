"""paddle.dataset.cifar readers (reference python/paddle/dataset/
cifar.py): samples are (3072 float32 pixels in [0, 1], int label)."""
from __future__ import annotations

import numpy as np

from ..vision.datasets import Cifar10, Cifar100

__all__ = ["train10", "test10", "train100", "test100"]


def _reader_creator(cls, mode):
    def reader():
        ds = cls(mode=mode)
        flat = ds.data.transpose(0, 3, 1, 2).reshape(len(ds), -1)
        for img, label in zip(flat, ds.labels):
            yield (img / 255.0).astype(np.float32), int(label)

    return reader


def train10():
    return _reader_creator(Cifar10, "train")


def test10():
    return _reader_creator(Cifar10, "test")


def train100():
    return _reader_creator(Cifar100, "train")


def test100():
    return _reader_creator(Cifar100, "test")

"""paddle.dataset.wmt16 readers (reference python/paddle/dataset/
wmt16.py)."""
from __future__ import annotations

import os

from .common import DATA_HOME
from ..text.datasets import WMT16 as _WMT16

__all__ = ["train", "test", "validation", "get_dict"]


def _path(data_file):
    return data_file or os.path.join(DATA_HOME, "wmt16", "wmt16.tar.gz")


def _reader_creator(mode, src_dict_size, trg_dict_size, src_lang,
                    data_file=None):
    def reader():
        ds = _WMT16(_path(data_file), mode=mode,
                    src_dict_size=src_dict_size,
                    trg_dict_size=trg_dict_size, lang=src_lang)
        for i in range(len(ds)):
            yield ds.src_ids[i], ds.trg_ids[i], ds.trg_ids_next[i]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return _reader_creator("train", src_dict_size, trg_dict_size,
                           src_lang, data_file)


def test(src_dict_size, trg_dict_size, src_lang="en", data_file=None):
    return _reader_creator("test", src_dict_size, trg_dict_size,
                           src_lang, data_file)


def validation(src_dict_size, trg_dict_size, src_lang="en",
               data_file=None):
    return _reader_creator("val", src_dict_size, trg_dict_size, src_lang,
                           data_file)


def get_dict(lang, dict_size, reverse=False, data_file=None):
    ds = _WMT16(_path(data_file), mode="train", src_dict_size=dict_size,
                trg_dict_size=dict_size, lang="en")
    return ds.get_dict(lang, reverse=reverse)

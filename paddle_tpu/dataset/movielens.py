"""paddle.dataset.movielens readers + meta helpers (reference
python/paddle/dataset/movielens.py)."""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME
from ..text.datasets import Movielens as _Movielens, _AGE_TABLE

__all__ = ["train", "test", "get_movie_title_dict", "max_movie_id",
           "max_user_id", "max_job_id", "movie_categories", "age_table",
           "user_info", "movie_info"]

age_table = list(_AGE_TABLE)

_meta = {}


def _dataset(mode="train", data_file=None):
    data_file = data_file or os.path.join(DATA_HOME, "movielens",
                                          "ml-1m.zip")
    return _Movielens(data_file, mode=mode)


def _get_meta(data_file=None):
    # cache keyed by the resolved archive path: a second call with a
    # DIFFERENT data_file must not silently reuse the first archive
    key = data_file or os.path.join(DATA_HOME, "movielens", "ml-1m.zip")
    if key not in _meta:
        _meta[key] = _dataset("train", data_file)
    return _meta[key]


def train(data_file=None):
    def reader():
        for i in range(len(_get_meta(data_file).data)):
            yield tuple(np.array(d)
                        for d in _get_meta(data_file).data[i])

    return reader


def test(data_file=None):
    ds = [None]

    def reader():
        if ds[0] is None:
            ds[0] = _dataset("test", data_file)
        for i in range(len(ds[0])):
            yield ds[0][i]

    return reader


def get_movie_title_dict(data_file=None):
    return _get_meta(data_file).movie_title_dict


def movie_categories(data_file=None):
    return _get_meta(data_file).categories_dict


def max_movie_id(data_file=None):
    return max(_get_meta(data_file).movie_info)


def max_user_id(data_file=None):
    return max(_get_meta(data_file).user_info)


def max_job_id(data_file=None):
    return max(u.job_id for u in _get_meta(data_file).user_info.values())


def movie_info(data_file=None):
    return _get_meta(data_file).movie_info


def user_info(data_file=None):
    return _get_meta(data_file).user_info

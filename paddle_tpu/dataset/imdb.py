"""paddle.dataset.imdb readers (reference python/paddle/dataset/
imdb.py): build_dict over the train split, (token-id doc, 0/1 label)
samples; pos label 0, neg label 1 — the reference's convention."""
from __future__ import annotations

import os
import re
import tarfile

from .common import DATA_HOME
from ..text.datasets import Imdb as _ImdbDataset

__all__ = ["build_dict", "train", "test", "word_dict"]


def _archive(data_file=None):
    path = data_file or os.path.join(DATA_HOME, "imdb",
                                     "aclImdb_v1.tar.gz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found (zero-egress environment — place the "
            f"standard aclImdb_v1.tar.gz there)")
    return path


def _tokenize(text):
    return re.compile(r"[^a-z0-9\s]").sub("", text.lower()).split()


def _docs(pattern, data_file=None):
    pat = re.compile(pattern)
    with tarfile.open(_archive(data_file), "r:*") as tf:
        for m in tf:
            if pat.match(m.name):
                yield _tokenize(
                    tf.extractfile(m).read().decode("utf-8", "ignore"))


def build_dict(pattern=r"aclImdb/train/(pos|neg)/.*\.txt$", cutoff=150,
               data_file=None):
    """Word dict over docs matching pattern, frequency > cutoff, <unk>
    last (reference imdb.py:59)."""
    from collections import Counter
    freq = Counter()
    for doc in _docs(pattern, data_file):
        freq.update(doc)
    items = [(w, c) for w, c in freq.items() if c > cutoff]
    items.sort(key=lambda t: (-t[1], t[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader_creator(split, word_idx, data_file=None):
    unk = word_idx["<unk>"]

    def reader():
        # pos docs first with label 0, then neg with label 1 — matching
        # the reference's two-queue interleave contract (labels, not
        # order, are what training consumes)
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = rf"aclImdb/{split}/{sub}/.*\.txt$"
            for doc in _docs(pattern, data_file):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def train(word_idx, data_file=None):
    return _reader_creator("train", word_idx, data_file)


def test(word_idx, data_file=None):
    return _reader_creator("test", word_idx, data_file)


def word_dict(data_file=None):
    return build_dict(data_file=data_file)

"""paddle.dataset.imikolov readers (reference python/paddle/dataset/
imikolov.py): PTB n-gram / seq samples under a caller-provided word
dict."""
from __future__ import annotations

import os
import tarfile

from .common import DATA_HOME

__all__ = ["build_dict", "train", "test", "DataType"]


class DataType:
    NGRAM = 1
    SEQ = 2


def _archive(data_file=None):
    path = data_file or os.path.join(DATA_HOME, "imikolov",
                                     "simple-examples.tgz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found (zero-egress environment — place the "
            f"standard simple-examples tarball there)")
    return path


def build_dict(min_word_freq=50, data_file=None):
    """Counts over train+valid with <s>/<e> per line, <unk> forced last
    (reference imikolov.py:54)."""
    from collections import Counter
    freq = Counter()
    with tarfile.open(_archive(data_file), "r:*") as tf:
        for split in ("train", "valid"):
            member = f"./simple-examples/data/ptb.{split}.txt"
            for raw in tf.extractfile(member):
                freq.update(raw.decode("utf-8").strip().split())
                freq.update(("<s>", "<e>"))
    freq.pop("<unk>", None)
    items = sorted(((w, c) for w, c in freq.items()
                    if c > min_word_freq), key=lambda t: (-t[1], t[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader_creator(split, word_idx, n, data_type, data_file=None):
    def reader():
        unk = word_idx["<unk>"]
        with tarfile.open(_archive(data_file), "r:*") as tf:
            member = f"./simple-examples/data/ptb.{split}.txt"
            for raw in tf.extractfile(member):
                toks = raw.decode("utf-8").strip().split()
                if data_type == DataType.NGRAM:
                    assert n > 0, "Invalid gram length"
                    framed = ["<s>"] + toks + ["<e>"]
                    if len(framed) < n:
                        continue
                    ids = [word_idx.get(w, unk) for w in framed]
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, unk) for w in toks]
                    src = [word_idx["<s>"]] + ids
                    trg = ids + [word_idx["<e>"]]
                    yield src, trg
                else:
                    raise ValueError(f"unknown data type {data_type}")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM, data_file=None):
    return _reader_creator("train", word_idx, n, data_type, data_file)


def test(word_idx, n, data_type=DataType.NGRAM, data_file=None):
    return _reader_creator("test", word_idx, n, data_type, data_file)

"""paddle.dataset.voc2012 readers (reference python/paddle/dataset/
voc2012.py)."""
from __future__ import annotations

from ..vision.datasets import VOC2012 as _VOC2012

__all__ = ["train", "test", "val"]


def _reader_creator(mode, data_file=None):
    def reader():
        ds = _VOC2012(data_file, mode=mode)
        for i in range(len(ds)):
            yield ds[i]

    return reader


def train(data_file=None):
    return _reader_creator("train", data_file)


def test(data_file=None):
    return _reader_creator("test", data_file)


def val(data_file=None):
    return _reader_creator("valid", data_file)

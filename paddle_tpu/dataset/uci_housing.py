"""paddle.dataset.uci_housing readers (reference python/paddle/dataset/
uci_housing.py): (13 normalized float features, 1 float target)."""
from __future__ import annotations

from ..text.datasets import UCIHousing

__all__ = ["train", "test"]


def _reader_creator(mode):
    def reader():
        ds = UCIHousing(mode=mode)
        for x, y in zip(ds.x, ds.y):
            yield x, y

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")

"""paddle.dataset.flowers readers (reference python/paddle/dataset/
flowers.py)."""
from __future__ import annotations

import os

from .common import DATA_HOME
from ..vision.datasets import Flowers as _Flowers

__all__ = ["train", "test", "valid"]


def _reader_creator(mode, data_file=None, label_file=None,
                    setid_file=None, mapper=None, cycle=False):
    def reader():
        base = os.path.join(DATA_HOME, "flowers")
        ds = _Flowers(
            data_file or os.path.join(base, "102flowers.tgz"),
            label_file or os.path.join(base, "imagelabels.mat"),
            setid_file or os.path.join(base, "setid.mat"), mode=mode)
        while True:
            for i in range(len(ds)):
                img, label = ds[i]
                if mapper is not None:
                    img = mapper(img)
                # labels stay 1-based like the reference reader
                yield img, int(label[0])
            if not cycle:
                break

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
          data_file=None, label_file=None, setid_file=None):
    """buffered_size/use_xmap are accepted for signature parity; the
    PIL decode here is cheap enough that the thread tiers are not
    wired (wrap with paddle_tpu.reader.xmap_readers for parallel
    mappers)."""
    return _reader_creator("train", data_file, label_file, setid_file,
                           mapper, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False,
         data_file=None, label_file=None, setid_file=None):
    return _reader_creator("test", data_file, label_file, setid_file,
                           mapper, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True,
          data_file=None, label_file=None, setid_file=None):
    return _reader_creator("valid", data_file, label_file, setid_file,
                           mapper)

"""paddle.dataset.image helpers (reference python/paddle/dataset/
image.py — cv2-based; here PIL/numpy): resize/crop/flip/transform for
reader pipelines.  Images are HWC uint8/float ndarrays; output of
simple_transform is CHW float32 like the reference."""
from __future__ import annotations

import numpy as np

__all__ = ["load_image", "load_image_bytes", "resize_short",
           "center_crop", "random_crop", "left_right_flip",
           "to_chw", "simple_transform"]


def load_image(path, is_color=True):
    from PIL import Image
    img = Image.open(path)
    img = img.convert("RGB" if is_color else "L")
    arr = np.array(img)
    return arr if is_color else arr[:, :, None]


def load_image_bytes(data, is_color=True):
    import io
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    arr = np.array(img)
    return arr if is_color else arr[:, :, None]


def resize_short(im, size):
    """Scale so the SHORT side equals size (aspect preserved)."""
    from PIL import Image
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    squeeze = im.ndim == 3 and im.shape[2] == 1
    pil = Image.fromarray(im[:, :, 0] if squeeze else im)
    out = np.array(pil.resize((nw, nh), Image.BILINEAR))
    return out[:, :, None] if squeeze else out


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    top = (h - size) // 2
    left = (w - size) // 2
    return im[top:top + size, left:left + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    top = rng.randint(0, h - size + 1)
    left = rng.randint(0, w - size + 1)
    return im[top:top + size, left:left + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """resize_short -> (random crop + flip | center crop) -> CHW float32
    -> optional mean subtraction (reference image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        im -= mean if mean.ndim != 1 else mean[:, None, None]
    return im

"""paddle.dataset — fluid-era reader-creator dataset API (reference
python/paddle/dataset/): `paddle.batch(paddle.reader.shuffle(
paddle.dataset.mnist.train(), 500), 64)`-style pipelines.  Parsing
delegates to the 2.0 Dataset classes (paddle_tpu.vision/text.datasets);
zero-egress: archives are read from DATA_HOME, never downloaded."""
from . import (cifar, common, conll05, flowers, image, imdb,  # noqa: F401
               imikolov, mnist, movielens, mq2007, uci_housing, voc2012,
               wmt14, wmt16)

__all__ = ["cifar", "common", "conll05", "flowers", "image", "imdb",
           "imikolov", "mnist", "movielens", "mq2007", "uci_housing",
           "voc2012", "wmt14", "wmt16"]

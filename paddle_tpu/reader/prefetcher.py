"""Async double-buffered feed prefetch — overlap host→device transfer of
batch N+1 with device compute of batch N.

The executor hot loop used to block on a synchronous `jnp.asarray` /
`device_put` of every feed before dispatching the step.  `Prefetcher`
moves that placement onto a worker thread behind a small bounded queue
(depth 2 = classic double buffering): while the device chews on step N,
the host is already casting + shipping step N+1's arrays.  On a
high-latency dispatch link (the axon TPU tunnel) this hides the entire
transfer; on CPU it still hides the int-cast + layout copy.

Contracts (tests/test_compile_cache.py):
  * order-preserving — one worker thread, FIFO queue;
  * exception-propagating — a worker error re-raises at the consumer's
    `next()` call *after* all batches that preceded it;
  * bounded — at most `depth` placed batches exist ahead of the consumer,
    so device memory for staged feeds is capped;
  * closeable — `close()` (or exhausting the iterator, or `with` exit)
    stops the worker without deadlocking on a full queue.
"""
from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["Prefetcher", "place_feed"]

_END = object()


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


def _canonical_array(arr, x64: bool):
    """Cast 64-bit host arrays down BEFORE device_put on x64-disabled
    backends — jnp would truncate anyway, but with a per-call UserWarning
    and an extra on-device cast (the BENCH_r05 tail was full of them)."""
    import numpy as np
    from ..core.dtype import canonical_np_dtype
    a = np.asarray(arr)
    tgt = canonical_np_dtype(a.dtype, x64)
    return a if tgt == a.dtype else a.astype(tgt)


def place_feed(feed: Any, device=None, sharding=None):
    """Ship one batch to the device: dict values / list items / bare
    arrays each get the x64-aware cast + `jax.device_put`.  Values that
    are already `jax.Array`s pass through untouched (idempotent, so a
    pre-staged feed can ride the same code path)."""
    import jax

    target = sharding if sharding is not None else device

    def _one(v):
        if isinstance(v, jax.Array):
            return v if target is None else jax.device_put(v, target)
        v = _canonical_array(v, _x64_enabled())
        return jax.device_put(v, target)

    if isinstance(feed, dict):
        return {k: _one(v) for k, v in feed.items()}
    if isinstance(feed, (list, tuple)):
        return type(feed)(_one(v) for v in feed)
    return _one(feed)


class Prefetcher:
    """Iterate `source`, applying `place_fn` on a background thread,
    `depth` batches ahead of the consumer.

        for feed in Prefetcher(batches, depth=2):
            exe.run(main, feed=feed, fetch_list=[])

    `place_fn` defaults to :func:`place_feed` (device placement with the
    x64-aware integer cast); pass `device=`/`sharding=` to aim it, or a
    custom callable (e.g. ``CompiledProgram.place_feed`` for the
    dp-sharded path).  ``place_fn=None`` with ``place=False`` turns the
    Prefetcher into a plain read-ahead buffer.
    """

    def __init__(self, source: Iterable, depth: int = 2,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 device=None, sharding=None, place: bool = True):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if place_fn is None and place:
            place_fn = lambda b: place_feed(b, device=device,  # noqa: E731
                                            sharding=sharding)
        self._place_fn = place_fn or (lambda b: b)
        self._source = iter(source)
        self.position = 0  # batches HANDED TO the consumer (checkpointable
        # resume cursor: staged-but-unconsumed batches are not counted, so
        # a restart re-reads them instead of skipping them)
        self._q: "_queue.Queue" = _queue.Queue(maxsize=depth)
        self._err: Optional[BaseException] = None
        self._closed = threading.Event()
        self._done = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="paddle-tpu-prefetch")
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _worker(self):
        try:
            for item in self._source:
                # closed-check BEFORE placing: a close() racing a blocked
                # put must not pull + device_put yet another source batch
                if self._closed.is_set():
                    return
                staged = self._place_fn(item)
                if not self._put(staged):
                    return  # closed mid-stream; drop silently
        except BaseException as e:  # noqa: BLE001 - re-raised at consumer
            self._err = e
        finally:
            self._put(_END)

    def _put(self, item) -> bool:
        # bounded put that never deadlocks against close(): poll the
        # closed flag instead of blocking forever on a full queue
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item is _END:
            self._done = True
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        self.position += 1
        return item

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        """Stop the worker and release staged batches.  Idempotent."""
        self._closed.set()

        def drain():
            while True:
                try:
                    self._q.get_nowait()
                except _queue.Empty:
                    break

        drain()  # unblock a worker stuck on put()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        drain()  # an in-flight put may have slipped into the freed slot

"""paddle.reader — reader-creator decorators (reference
python/paddle/reader/decorator.py).

A *reader* is a zero-arg callable returning an iterable of samples; a
*reader creator* builds readers.  These combinators are the fluid-era
input pipeline (`paddle.batch(paddle.reader.shuffle(mnist.train(),
500), 64)`); the 2.0 path is paddle_tpu.io.DataLoader.
"""
from __future__ import annotations

import itertools
import queue as _queue
import random
import threading

from .prefetcher import Prefetcher, place_feed

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "xmap_readers", "cache", "ComposeNotAligned",
           "Prefetcher", "place_feed"]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Reader whose samples are func applied across the given readers'
    samples (decorator.py:91)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a buf_size window, yield it shuffled
    (decorator.py:133)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back to back (decorator.py:182)."""

    def reader():
        for r in readers:
            for e in r():
                yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples: (a, (b, c)) -> (a, b, c)
    (decorator.py:247).  check_alignment=True raises ComposeNotAligned
    when the readers run out at different lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Producer-thread read-ahead of up to `size` samples
    (decorator.py:307)."""

    end = object()

    def data_reader():
        q = _queue.Queue(maxsize=size)
        err = []

        def produce():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e
        if err:
            # a swallowed producer error would look like a short-but-
            # successful epoch — propagate it instead
            raise err[0]

    return data_reader


def firstn(reader, n):
    """First n samples only (decorator.py:366)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads
    (decorator.py:411).  order=True preserves input order."""

    end = object()

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        errs = []

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errs.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        break
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errs.append(e)
            finally:
                # the sentinel must go out even when the mapper raised,
                # or the consumer loop below waits forever
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
        else:
            pending = {}
            next_i = 0
            while finished < process_num or pending:
                if next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
                    continue
                if finished == process_num:
                    break  # remaining pending have a gap: error upstream
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                pending[item[0]] = item[1]
        if errs:
            raise errs[0]

    return data_reader


def cache(reader):
    """Materialize the reader's samples once; replay from memory
    (decorator.py:55)."""
    all_data = None

    def cache_reader():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        for d in all_data:
            yield d

    return cache_reader

"""fluid.regularizer (reference python/paddle/fluid/regularizer.py)."""
from ..static.optimizer import L1Decay, L2Decay  # noqa: F401

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer",
           "L2DecayRegularizer"]

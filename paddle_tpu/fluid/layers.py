"""fluid.layers — the merged layer namespace Fluid code expects: nn +
tensor + control_flow + metric ops in ONE module (reference
python/paddle/fluid/layers/__init__.py merges its submodules the same
way)."""
from ..static.layers import *  # noqa: F401,F403
from ..static.layers import __all__ as _layers_all
from ..static.control_flow import *  # noqa: F401,F403
from ..static.control_flow import __all__ as _cf_all
from ..static import layers as _static_layers

__all__ = list(_layers_all) + list(_cf_all)


def data(name, shape, dtype="float32", lod_level=0,
         append_batch_size=True):
    """fluid.layers.data keeps the REFERENCE default
    append_batch_size=True (shape=[13] means [-1, 13]); the 2.0-style
    paddle_tpu.static.layers.data takes the full shape."""
    return _static_layers.data(name, shape, dtype=dtype,
                               lod_level=lod_level,
                               append_batch_size=append_batch_size)

"""paddle.fluid — compatibility namespace for Fluid-era user code
(`import paddle.fluid as fluid`).  Every symbol is a re-export of this
framework's own modules; nothing lives here.  Reference surface:
python/paddle/fluid/__init__.py."""
from ..core.program import (  # noqa: F401
    Program, program_guard, default_main_program,
    default_startup_program, name_scope, unique_name, device_guard,
)
from ..core.program import VarDesc as Variable  # noqa: F401
from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XLAPlace, TPUPlace,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
)
from ..core import flags as core  # noqa: F401
from ..core.flags import get_flags, set_flags  # noqa: F401
from ..static.executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard,
)
from ..static import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy, ParallelExecutor,
    ExponentialMovingAverage,
    save_inference_model, load_inference_model, load_program_state,
    set_program_state,
)
from ..static.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from ..static.backward import append_backward, gradients  # noqa: F401
from ..io.data_feeder import DataFeeder  # noqa: F401
from ..core.generator import seed as _seed  # noqa: F401

from ..static import layers as _static_layers  # noqa: F401
from . import layers  # noqa: F401
from ..static import optimizer  # noqa: F401
from ..static import initializer  # noqa: F401
from ..static import nets  # noqa: F401
from . import io  # noqa: F401
from .. import dygraph  # noqa: F401
from ..static.optimizer import (  # noqa: F401
    L1Decay, L2Decay, GradientClipByValue, GradientClipByNorm,
    GradientClipByGlobalNorm,
)
from . import regularizer, clip  # noqa: F401
from ..metric import metrics  # noqa: F401
from ..io.dataloader import DataLoader as _DataLoader  # noqa: F401


def embedding(*args, **kwargs):
    """fluid.embedding == fluid.layers.embedding (v2 semantics)."""
    return layers.embedding(*args, **kwargs)


def one_hot(*args, **kwargs):
    return layers.one_hot(*args, **kwargs)


def in_dygraph_mode():
    from ..dygraph.base import in_dygraph_mode as _f
    return _f()


def enable_dygraph(place=None):
    from ..dygraph.base import enable_dygraph as _f
    return _f(place)


def disable_dygraph():
    from ..dygraph.base import disable_dygraph as _f
    return _f()

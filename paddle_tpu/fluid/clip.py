"""fluid.clip (reference python/paddle/fluid/clip.py)."""
from ..static.optimizer import (  # noqa: F401
    GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
)

__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm"]

"""fluid.io — save/load surface (reference python/paddle/fluid/io.py)."""
from ..io.framework_io import (  # noqa: F401
    save_vars, save_params, save_persistables, load_vars, load_params,
    load_persistables, save_inference_model, load_inference_model,
    set_program_state, load_program_state,
)
from ..io.framework_io import static_save as save  # noqa: F401
from ..io.framework_io import static_load as load  # noqa: F401
from ..io.dataloader import DataLoader  # noqa: F401
from ..io.generator_loader import GeneratorLoader as PyReader  # noqa: F401

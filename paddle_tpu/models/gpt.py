"""GPT-style decoder-only language model + generation API.

Reference surface: the fluid-era transformer decode loop
(beam_search_op.cc / beam_search_decode_op.cc driving seq2seq decode) and
the 2.x `generate()` contract (greedy / sampling / beam search).  The
reference repo carries decoder LMs through its transformer examples; a
decoder-only family is the capability users reach for first on TPU, so it
ships as a first-class model here.

TPU design: attention runs through MultiHeadAttention with an explicit
additive causal mask (cached per sequence length; the dense-mask path —
flash attention's mask-free causal route is a follow-up once MHA grows a
`causal` flag).  Generation is host-orchestrated over the registered
`beam_search` op (dense [batch, beam] axis, shared loop in
models/_decode.py) exactly like TransformerModel.beam_search.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu
from .. import nn
from ..dygraph.layers import Layer

__all__ = ["GPTConfig", "GPTModel", "GPTForGeneration", "gpt_small"]


class GPTConfig:
    def __init__(self, vocab_size=5000, hidden_size=256, num_layers=4,
                 num_heads=4, intermediate_size=None, max_position=512,
                 bos_id=0, eos_id=1, dropout=0.1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or hidden_size * 4
        self.max_position = max_position
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.dropout = dropout


class _Block(Layer):
    """Pre-norm decoder block (GPT-2 style)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)

    def forward(self, x, mask, cache=None):
        h = self.ln1(x)
        if cache is None:
            x = x + self.attn(h, h, h, attn_mask=mask)
        else:
            # incremental decode: attn consumes + extends the per-layer
            # KV cache (MultiHeadAttention.Cache concat path)
            out, cache = self.attn(h, h, h, attn_mask=mask, cache=cache)
            x = x + out
        h = self.ln2(x)
        x = x + self.fc2(nn.functional.gelu(self.fc1(h)))
        return x if cache is None else (x, cache)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig = None, **kw):
        super().__init__()
        self.config = cfg or GPTConfig(**kw)
        c = self.config
        self.wte = nn.Embedding(c.vocab_size, c.hidden_size)
        self.wpe = nn.Embedding(c.max_position, c.hidden_size)
        self.blocks = nn.LayerList([_Block(c) for _ in range(c.num_layers)])
        self.ln_f = nn.LayerNorm(c.hidden_size)
        self._mask_cache = {}

    def _mask(self, seq):
        # cache per length: decode loops call every step and should not
        # re-upload an [S, S] mask host->device each time
        m = self._mask_cache.get(seq)
        if m is None:
            m = paddle_tpu.to_tensor(
                np.triu(np.full((seq, seq), -1e9, np.float32), k=1))
            self._mask_cache[seq] = m
        return m

    def forward(self, input_ids, cache=None, pos_offset=None,
                attn_mask=None):
        """Plain LM forward, or — when ``cache`` (list of per-block
        ``MultiHeadAttention.Cache``) is given — one incremental decode
        step that returns ``(logits, new_caches)``.

        ``pos_offset``: per-row absolute position of ``input_ids[:, 0]``
        (int array [batch]); continuous batching feeds sequences of
        different lengths in one fixed-slot batch, so each row needs its
        own position base.  ``attn_mask`` overrides the causal mask —
        the serving engine passes an additive mask that hides each
        slot's KV padding columns."""
        seq = input_ids.shape[1]
        if pos_offset is None:
            pos = paddle_tpu.to_tensor(
                np.arange(seq, dtype=np.int64)[None].repeat(
                    input_ids.shape[0], 0))
        else:
            off = np.asarray(pos_offset, np.int64).reshape(-1, 1)
            pos = paddle_tpu.to_tensor(
                off + np.arange(seq, dtype=np.int64)[None])
        x = self.wte(input_ids) + self.wpe(pos)
        mask = attn_mask if attn_mask is not None else self._mask(seq)
        if cache is None:
            for blk in self.blocks:
                x = blk(x, mask)
            x = self.ln_f(x)
            # tied LM head
            return paddle_tpu.matmul(x, self.wte.weight, transpose_y=True)
        new_caches = []
        for blk, c in zip(self.blocks, cache):
            x, c = blk(x, mask, cache=c)
            new_caches.append(c)
        x = self.ln_f(x)
        logits = paddle_tpu.matmul(x, self.wte.weight, transpose_y=True)
        return logits, new_caches

    def gen_cache(self, batch_size):
        """Fresh empty per-block KV caches for ``batch_size`` rows (the
        serving engine's slot-admission entry point)."""
        c = self.config
        head_dim = c.hidden_size // c.num_heads
        return [nn.MultiHeadAttention.Cache(
            paddle_tpu.to_tensor(np.zeros(
                (batch_size, c.num_heads, 0, head_dim), np.float32)),
            paddle_tpu.to_tensor(np.zeros(
                (batch_size, c.num_heads, 0, head_dim), np.float32)))
            for _ in self.blocks]


class GPTForGeneration(Layer):
    """generate() with greedy / sampling / beam_search strategies (the
    paddle 2.x generation contract), built on the beam_search op."""

    def __init__(self, gpt: GPTModel):
        super().__init__()
        self.gpt = gpt

    def forward(self, input_ids):
        return self.gpt(input_ids)

    def generate(self, input_ids, max_length=20,
                 decode_strategy="greedy_search", num_beams=4, top_k=0,
                 temperature=1.0, seed=0, length_penalty=0.0):
        cfg = self.gpt.config
        ids = np.asarray(input_ids.numpy() if hasattr(input_ids, "numpy")
                         else input_ids).astype(np.int64)
        if decode_strategy not in ("greedy_search", "sampling",
                                   "beam_search"):
            raise ValueError(
                f"unknown decode_strategy {decode_strategy!r}; expected "
                "'greedy_search', 'sampling', or 'beam_search'")
        if ids.shape[1] + max_length > cfg.max_position:
            raise ValueError(
                f"prefix ({ids.shape[1]}) + max_length ({max_length}) "
                f"exceeds max_position ({cfg.max_position}); longer "
                "sequences would silently clamp position embeddings")
        if decode_strategy == "beam_search":
            return self._beam(ids, max_length, num_beams, length_penalty)
        rng = np.random.RandomState(seed)
        batch = ids.shape[0]
        finished = np.zeros(batch, bool)
        for _ in range(max_length):
            logits = np.asarray(self.gpt(
                paddle_tpu.to_tensor(ids)).numpy())[:, -1]
            if decode_strategy == "sampling":
                logits = logits / max(temperature, 1e-6)
                if top_k:
                    kth = np.sort(logits, -1)[:, -top_k][:, None]
                    logits = np.where(logits < kth, -1e9, logits)
                p = np.exp(logits - logits.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                nxt = np.array([rng.choice(p.shape[1], p=row)
                                for row in p])
            else:  # greedy_search
                nxt = logits.argmax(-1)
            nxt = np.where(finished, cfg.eos_id, nxt)
            finished |= nxt == cfg.eos_id
            ids = np.concatenate([ids, nxt[:, None].astype(np.int64)], 1)
            if finished.all():
                break
        return ids

    def _beam(self, ids, max_length, W, length_penalty=0.0):
        from ._decode import beam_search_loop

        def step_logits(trg):
            return np.asarray(self.gpt(
                paddle_tpu.to_tensor(trg)).numpy())[:, -1]

        return beam_search_loop(step_logits, ids, W, self.gpt.config.eos_id,
                                max_length, length_penalty)


def gpt_small(**kw):
    return GPTForGeneration(GPTModel(GPTConfig(
        hidden_size=256, num_layers=4, num_heads=4, **kw)))

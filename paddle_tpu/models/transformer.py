"""Transformer seq2seq (WMT en-de "transformer-big" family).

Reference capability: the reference's dist tests train
`dist_transformer.py`/`transformer` book models; BASELINE.md lists
Transformer-big WMT14 en-de as a benchmark config.  Architecture follows
the public "Attention Is All You Need" model over nn.Transformer.

TPU-first: sinusoidal position encoding precomputed host-side once;
decoding uses fixed-length greedy loop (static shapes — XLA-friendly).
"""
from __future__ import annotations

import jax
import numpy as np

import paddle_tpu
from .. import nn
from ..dygraph.layers import Layer

__all__ = ["TransformerConfig", "PositionalEncoding", "TransformerModel",
           "CrossEntropyCriterion", "transformer_base", "transformer_big"]


class TransformerConfig:
    def __init__(self, src_vocab_size=30000, trg_vocab_size=30000,
                 max_length=256, d_model=512, n_head=8, num_encoder_layers=6,
                 num_decoder_layers=6, d_inner_hid=2048, dropout=0.1,
                 weight_sharing=True, bos_id=0, eos_id=1):
        self.src_vocab_size = src_vocab_size
        self.trg_vocab_size = trg_vocab_size
        self.max_length = max_length
        self.d_model = d_model
        self.n_head = n_head
        self.num_encoder_layers = num_encoder_layers
        self.num_decoder_layers = num_decoder_layers
        self.d_inner_hid = d_inner_hid
        self.dropout = dropout
        self.weight_sharing = weight_sharing
        self.bos_id = bos_id
        self.eos_id = eos_id


class PositionalEncoding(Layer):
    def __init__(self, d_model, max_len=1024, dropout=0.1):
        super().__init__()
        pe = np.zeros((max_len, d_model), np.float32)
        pos = np.arange(max_len, dtype=np.float32)[:, None]
        div = np.exp(np.arange(0, d_model, 2, dtype=np.float32)
                     * -(np.log(10000.0) / d_model))
        pe[:, 0::2] = np.sin(pos * div)
        pe[:, 1::2] = np.cos(pos * div)
        self.register_buffer("pe", paddle_tpu.to_tensor(pe),
                             persistable=False)
        self.dropout = nn.Dropout(dropout)
        self.scale = float(np.sqrt(d_model))

    def forward(self, x):
        seq = x.shape[1]
        return self.dropout(x * self.scale + self.pe[:seq])


class TransformerModel(Layer):
    """Embeddings + nn.Transformer + tied generator."""

    def __init__(self, cfg: TransformerConfig = None, **kw):
        super().__init__()
        cfg = cfg or TransformerConfig(**kw)
        self.config = cfg
        self.src_emb = nn.Embedding(cfg.src_vocab_size, cfg.d_model)
        if cfg.weight_sharing and cfg.src_vocab_size == cfg.trg_vocab_size:
            self.trg_emb = self.src_emb
        else:
            self.trg_emb = nn.Embedding(cfg.trg_vocab_size, cfg.d_model)
        self.pos_enc = PositionalEncoding(cfg.d_model, cfg.max_length,
                                          cfg.dropout)
        self.transformer = nn.Transformer(
            d_model=cfg.d_model, nhead=cfg.n_head,
            num_encoder_layers=cfg.num_encoder_layers,
            num_decoder_layers=cfg.num_decoder_layers,
            dim_feedforward=cfg.d_inner_hid, dropout=cfg.dropout)
        # generator tied to target embedding
        self._tied_out = self.trg_emb.weight

    def _causal_mask(self, seq):
        m = np.triu(np.full((seq, seq), -1e9, np.float32), k=1)
        return paddle_tpu.to_tensor(m)

    def forward(self, src_ids, trg_ids, src_pad_mask=None):
        """Returns logits [B, T, V]."""
        src = self.pos_enc(self.src_emb(src_ids))
        trg = self.pos_enc(self.trg_emb(trg_ids))
        tgt_mask = self._causal_mask(trg_ids.shape[1])
        memory_mask = None
        if src_pad_mask is not None:
            am = src_pad_mask.astype("float32")
            memory_mask = (am[:, None, None, :] - 1.0) * 1e4
        out = self.transformer(src, trg, src_mask=memory_mask,
                               tgt_mask=tgt_mask,
                               memory_mask=memory_mask)
        logits = paddle_tpu.matmul(out, self._tied_out, transpose_y=True)
        return logits

    def beam_search(self, src_ids, beam_size=1, max_len=None,
                    length_penalty=0.0):
        """Beam-search decode over the `beam_search` op (beam_search_op.cc
        semantics; beam_size=1 degrades to greedy).  The encoder runs ONCE
        and its memory is tiled per beam; each step scores [B*W, V], the
        op selects the top-W continuations per batch group, and the
        candidate histories are re-gathered by parent index (host-side
        orchestration like the reference's Transformer decode loop)."""
        import jax.numpy as jnp
        from ._decode import beam_search_loop
        cfg = self.config
        W = max(1, int(beam_size))
        max_len = max_len or min(cfg.max_length, src_ids.shape[1] * 2)
        batch = src_ids.shape[0]
        memory = self.transformer.encoder(
            self.pos_enc(self.src_emb(src_ids)), None)
        # tile memory rows per beam: [B, S, D] -> [B*W, S, D]
        mem = paddle_tpu.to_tensor(jnp.repeat(
            memory._value if hasattr(memory, "_value")
            else jnp.asarray(memory.numpy()), W, axis=0))

        def step_logits(trg):
            t = self.pos_enc(self.trg_emb(paddle_tpu.to_tensor(trg)))
            out = self.transformer.decoder(
                t, mem, self._causal_mask(trg.shape[1]), None)
            logits = paddle_tpu.matmul(out, self._tied_out,
                                       transpose_y=True)
            return np.asarray(logits.numpy())[:, -1]

        init = np.full((batch, 1), cfg.bos_id, np.int64)
        return beam_search_loop(step_logits, init, W, cfg.eos_id,
                                max_len - 1, length_penalty)


class CrossEntropyCriterion(Layer):
    """label-smoothed CE over non-pad tokens (transformer training loss)."""

    def __init__(self, label_smooth_eps=0.1, pad_id=-100):
        super().__init__()
        self.eps = label_smooth_eps
        self.pad_id = pad_id

    def forward(self, logits, labels):
        import paddle_tpu.nn.functional as F
        vocab = logits.shape[-1]
        flat = logits.reshape([-1, vocab])
        lab = labels.reshape([-1])
        logp = F.log_softmax(flat, axis=-1)
        nll = -paddle_tpu.gather_nd(
            logp, paddle_tpu.stack(
                [paddle_tpu.arange(0, lab.shape[0], dtype="int64"),
                 lab.astype("int64")], axis=1))
        if self.eps > 0:
            smooth = -logp.mean(axis=-1)
            nll = (1 - self.eps) * nll + self.eps * smooth
        mask = (lab != self.pad_id).astype("float32")
        return (nll * mask).sum() / (mask.sum() + 1e-9)


def transformer_base(**kw):
    return TransformerModel(TransformerConfig(**kw))


def transformer_big(**kw):
    kw.setdefault("d_model", 1024)
    kw.setdefault("n_head", 16)
    kw.setdefault("d_inner_hid", 4096)
    kw.setdefault("dropout", 0.3)
    return TransformerModel(TransformerConfig(**kw))

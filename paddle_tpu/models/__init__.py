"""Model families (reference P18 model zoo + the BASELINE.md benchmark
configs: LeNet/ResNet in paddle_tpu.vision.models; BERT/ERNIE and
Transformer here)."""
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForPretraining, BertPretrainingCriterion,
    ErnieModel, ErnieForPretraining, bert_base, bert_large, ernie_base,
)
from .transformer import (  # noqa: F401
    TransformerConfig, TransformerModel, CrossEntropyCriterion,
    transformer_base, transformer_big,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForGeneration, gpt_small,
)
from .static_lm import build_transformer_lm  # noqa: F401

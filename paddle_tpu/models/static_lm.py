"""Static-graph transformer LM builder with optional tensor parallelism.

The v5e-32-scale rehearsal config: assembles embedding → N pre-LN
transformer blocks → LM head as ONE static Program.  With
`tensor_parallel_degree > 1` every block uses the Megatron layers
(distributed/tensor_parallel.py): column/row-parallel attention + MLP,
weights annotated for the "tp" mesh axis — run it under
CompiledProgram(BuildStrategy.tensor_parallel_degree=tp) or through
fleet's DistributedStrategy.tensor_parallel.

(The dygraph model families live in models/gpt.py / models/bert.py; this
is the static counterpart the ERNIE-style pretrain configs use.)
"""
from __future__ import annotations

from ..static import layers

__all__ = ["build_transformer_lm"]


def build_transformer_lm(vocab_size, hidden, num_layers, num_heads, seq_len,
                         tensor_parallel_degree=1,
                         sequence_parallel=False):
    """Returns (main_program, startup_program, loss, logits); feeds are
    int64 `ids` [batch, seq_len], `pos` [batch, seq_len] (position ids,
    typically np.tile(np.arange(seq_len), (batch, 1))), and `labels`
    [batch, seq_len, 1].

    Attention is BIDIRECTIONAL (BERT/ERNIE-style MLM rehearsal — the
    bench's north-star config): feed masked-token labels, not shifted
    next-token labels.  For causal decoding use models.GPTModel.

    ``sequence_parallel=True`` routes every layer's attention through
    the `ring_attention` op: run the program via
    ``CompiledProgram(BuildStrategy.sequence_parallel_degree=n)`` and
    the sequence dim shards over the "sp" mesh axis with K/V rotating
    around the ring (the long-context path — no S² scores tensor).  On
    a single device the op degrades to plain attention, so the same
    program also runs for CPU debugging.  Composes with
    FLAGS_recompute auto-remat (checkpoints select at layer boundaries
    around the ring op like any attention core)."""
    import paddle_tpu.static as static
    from ..distributed.tensor_parallel import (parallel_attention,
                                               col_parallel_fc,
                                               row_parallel_fc)
    import paddle_tpu.static.nets as nets

    tp = max(1, int(tensor_parallel_degree))
    if sequence_parallel and tp > 1:
        raise ValueError("sequence_parallel and tensor_parallel_degree>1 "
                         "cannot combine in one program (mesh has one "
                         "model axis; see CompiledProgram._get_mesh)")
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, seq_len], dtype="int64")
        pos = layers.data("pos", [-1, seq_len], dtype="int64")
        labels = layers.data("labels", [-1, seq_len, 1], dtype="int64")
        h = layers.elementwise_add(
            layers.embedding(ids, size=[vocab_size, hidden]),
            layers.embedding(pos, size=[seq_len, hidden]))
        for _ in range(num_layers):
            a_in = layers.layer_norm(h, begin_norm_axis=2)
            if tp > 1:
                attn = parallel_attention(a_in, hidden, num_heads, tp)
            else:
                q = layers.fc(a_in, hidden, num_flatten_dims=2)
                k = layers.fc(a_in, hidden, num_flatten_dims=2)
                v = layers.fc(a_in, hidden, num_flatten_dims=2)
                ctx = nets.scaled_dot_product_attention(
                    q, k, v, num_heads=num_heads,
                    sequence_parallel=sequence_parallel)
                attn = layers.fc(ctx, hidden, num_flatten_dims=2)
            h = layers.elementwise_add(h, attn)
            m_in = layers.layer_norm(h, begin_norm_axis=2)
            if tp > 1:
                m = col_parallel_fc(m_in, hidden * 4, num_flatten_dims=2,
                                    act="gelu", tp_degree=tp)
                m = row_parallel_fc(m, hidden, num_flatten_dims=2,
                                    tp_degree=tp)
            else:
                m = layers.fc(m_in, hidden * 4, num_flatten_dims=2,
                              act="gelu")
                m = layers.fc(m, hidden, num_flatten_dims=2)
            h = layers.elementwise_add(h, m)
        h = layers.layer_norm(h, begin_norm_axis=2)
        logits = layers.fc(h, vocab_size, num_flatten_dims=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, labels))
    return main, startup, loss, logits

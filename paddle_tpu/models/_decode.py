"""Shared host-side beam-search orchestration for model decode loops
(reference: the transformer example's decode loop over beam_search_op.cc).
Models supply a callback producing next-token logits for the current
[batch*beam, T] candidate matrix; the loop drives the registered
`beam_search` op and re-gathers histories by parent index."""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["beam_search_loop"]


def beam_search_loop(step_logits: Callable[[np.ndarray], np.ndarray],
                     init_ids: np.ndarray, beam_size: int, eos_id: int,
                     max_steps: int, length_penalty: float = 0.0):
    """Returns the best hypothesis per batch group, [batch, T].

    length_penalty: GNMT alpha — final ranking uses
    score / ((5 + len) / 6) ** alpha (0.0 = raw cumulative log-prob)."""
    from ..ops.registry import run_kernel, OpContext
    W = max(1, int(beam_size))
    batch, prefix = init_ids.shape
    trg = np.repeat(init_ids, W, axis=0)          # [B*W, prefix]
    pre_scores = np.zeros((batch * W, 1), np.float32)
    ctx = OpContext()
    for step in range(max_steps):
        logits = step_logits(trg)                 # [B*W, V]
        logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        sel = run_kernel(
            "beam_search",
            {"pre_ids": jnp.asarray(trg[:, -1:]),
             "pre_scores": jnp.asarray(pre_scores),
             "scores": logp, "ids": None},
            {"beam_size": W, "end_id": eos_id,
             "first_step": step == 0}, ctx)
        tokens = np.asarray(sel["selected_ids"]).reshape(-1, 1)
        pre_scores = np.asarray(sel["selected_scores"])
        parents = np.asarray(sel["parent_idx"]).reshape(-1)
        trg = np.concatenate([trg[parents], tokens.astype(np.int64)], 1)
        if (trg[:, -1] == eos_id).all():
            break
    # final ranking with GNMT length normalization
    gen = trg[:, prefix:]
    lens = np.where((gen == eos_id).any(1),
                    (gen == eos_id).argmax(1) + 1, gen.shape[1])
    norm = ((5.0 + lens) / 6.0) ** float(length_penalty)
    ranked = (pre_scores[:, 0] / norm).reshape(batch, W)
    best = ranked.argmax(1)
    return trg.reshape(batch, W, -1)[np.arange(batch), best]

"""BERT / ERNIE encoder family — the flagship pretraining model.

Reference capability: the reference ships fused BERT inference kernels
(operators/fused/multihead_matmul_op.cu, math/bert_encoder_functor.cu) and
its north-star workload is ERNIE-base pretraining (BASELINE.md).  Model
structure follows the public BERT/ERNIE-1.0 architecture (post-LN
transformer encoder, learned position embeddings, MLM + NSP heads).

TPU-first notes: all matmuls keep [batch*seq, hidden]-friendly shapes for
MXU tiling; dtype is parameterised so AMP/bf16 flows through; the encoder
reuses nn.TransformerEncoder whose attention lowers to the flash/ring
Pallas kernels when enabled (paddle_tpu.ops.attention).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu
from .. import nn
from ..dygraph.layers import Layer

__all__ = ["BertConfig", "BertEmbeddings", "BertPooler", "BertModel",
           "BertForPretraining", "BertPretrainingCriterion", "ErnieModel",
           "ErnieForPretraining", "bert_base", "bert_large", "ernie_base"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id


class BertEmbeddings(Layer):
    """word + position + token-type embeddings, LN, dropout."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq = input_ids.shape[-1]
        if position_ids is None:
            position_ids = paddle_tpu.to_tensor(
                np.arange(seq, dtype=np.int64)[None, :])
        if token_type_ids is None:
            token_type_ids = paddle_tpu.to_tensor(
                np.zeros((1, seq), dtype=np.int64))
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class BertModel(Layer):
    """Embeddings + TransformerEncoder + pooler."""

    def __init__(self, cfg: BertConfig = None, **kw):
        super().__init__()
        cfg = cfg or BertConfig(**kw)
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask → additive [B, 1, 1, S]
            am = attention_mask.astype("float32")
            attention_mask = (am[:, None, None, :] - 1.0) * 1e4
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq_out = self.encoder(emb, src_mask=attention_mask)
        pooled = self.pooler(seq_out)
        return seq_out, pooled


class BertLMPredictionHead(Layer):
    """MLM head: transform + LN + decoder tied to word embeddings."""

    def __init__(self, cfg: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = nn.GELU()
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self._tied = embedding_weights  # ParamBase [V, H]
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)

    def forward(self, hidden):
        h = self.layer_norm(self.activation(self.transform(hidden)))
        logits = paddle_tpu.matmul(h, self._tied, transpose_y=True) \
            + self.decoder_bias
        return logits


class BertForPretraining(Layer):
    """MLM + NSP heads over BertModel (bert pretraining parity)."""

    def __init__(self, bert: BertModel):
        super().__init__()
        self.bert = bert
        cfg = bert.config
        self.cls = BertLMPredictionHead(
            cfg, bert.embeddings.word_embeddings.weight)
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    position_ids, attention_mask)
        prediction_scores = self.cls(seq_out)
        seq_relationship_score = self.seq_relationship(pooled)
        return prediction_scores, seq_relationship_score


class BertPretrainingCriterion(Layer):
    """masked-LM + NSP loss."""

    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size
        self.ce = nn.CrossEntropyLoss(reduction="none")

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None,
                masked_lm_weights=None):
        logits = prediction_scores.reshape([-1, self.vocab_size])
        labels = masked_lm_labels.reshape([-1])
        mlm_loss = self.ce(logits, labels)
        if masked_lm_weights is not None:
            w = masked_lm_weights.reshape([-1]).astype("float32")
            mlm_loss = (mlm_loss * w).sum() / (w.sum() + 1e-6)
        else:
            mlm_loss = mlm_loss.mean()
        if next_sentence_labels is None:
            return mlm_loss
        nsp_loss = self.ce(seq_relationship_score,
                           next_sentence_labels.reshape([-1])).mean()
        return mlm_loss + nsp_loss


# ERNIE-1.0 shares the BERT architecture (different pretraining masking —
# phrase/entity level — which is a data-pipeline property, not a model one)
ErnieModel = BertModel
ErnieForPretraining = BertForPretraining


def bert_base(**kw):
    return BertModel(BertConfig(**kw))


def bert_large(**kw):
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_hidden_layers", 24)
    kw.setdefault("num_attention_heads", 16)
    kw.setdefault("intermediate_size", 4096)
    return BertModel(BertConfig(**kw))


def ernie_base(**kw):
    kw.setdefault("vocab_size", 18000)
    return ErnieModel(BertConfig(**kw))

"""paddle.incubate.reader (reference fluid/contrib/reader/
distributed_reader.py): shard a batch reader across PADDLE_TRAINERS_NUM
processes by round-robin on batch index."""
from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    """Each trainer keeps every trainers_num-th batch (offset by its
    PADDLE_TRAINER_ID), so the global stream partitions exactly."""
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    assert trainer_id < trainers_num, (trainer_id, trainers_num)

    def reader():
        for i, batch in enumerate(batch_reader()):
            if i % trainers_num == trainer_id:
                yield batch

    return reader

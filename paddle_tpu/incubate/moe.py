"""Mixture-of-Experts with switch (top-1) routing + expert parallelism.

Reference surface: paddle's incubate MoE work grew out of the Fluid-era
distributed stack (the `alltoall` collective in
python/paddle/distributed/collective.py and the expert-parallel designs
layered on it); SURVEY.md §5.7 lists the all-to-all expert path as a
first-class long-context/scale capability.

TPU-native design:
  * Routing is fully static-shape: top-1 expert choice, per-expert
    capacity C, dispatch/combine as scatter/gather into a dense
    [E, C, D] buffer (tokens over capacity are dropped, standard Switch
    semantics) — no ragged anything, XLA fuses the one-hot arithmetic.
  * Expert compute is ONE batched einsum over the expert axis — the MXU
    sees [E, C, D] x [E, D, H], not E small matmuls.
  * Expert parallelism: inside shard_map, expert weights are sharded over
    an `ep` mesh axis and dispatch rides `jax.lax.all_to_all` (the ICI
    collective the reference reaches via its alltoall op) — tokens travel
    to their expert's device and back.
  * Differentiable through routing the standard way: the top-1 choice is
    a constant of the backward; gradients flow through the gate
    probability scaling and the experts.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["switch_moe", "moe_aux_loss", "init_moe_params"]


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32):
    """(gate_w, w1, b1, w2, b2) — expert weights carry a leading E axis."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = (2.0 / (d_model + d_hidden)) ** 0.5
    return (jax.random.normal(k1, (d_model, n_experts), dtype) * 0.02,
            jax.random.normal(k2, (n_experts, d_model, d_hidden),
                              dtype) * s1,
            jnp.zeros((n_experts, d_hidden), dtype),
            jax.random.normal(k3, (n_experts, d_hidden, d_model),
                              dtype) * s1,
            jnp.zeros((n_experts, d_model), dtype))


def moe_aux_loss(gates, expert_idx):
    """Switch load-balancing loss: E * sum_e f_e * p_e (Switch Transformer
    eq. 4) — pushes the router toward uniform expert load."""
    E = gates.shape[-1]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=gates.dtype)
    f = onehot.mean(axis=0)          # fraction of tokens per expert
    p = gates.mean(axis=0)           # mean router prob per expert
    return E * jnp.sum(f * p)


def switch_moe(x, gate_w, w1, b1, w2, b2, capacity_factor: float = 1.25,
               axis_name: Optional[str] = None):
    """Top-1 MoE feed-forward.  x: [N, D] tokens.

    Without axis_name: w1/w2 hold ALL experts ([E, D, H] / [E, H, D]).
    With axis_name (inside shard_map): w1/w2 hold this device's expert
    shard ([E_local, ...]); dispatch all_to_alls tokens across the `ep`
    axis so each device runs only its local experts.

    Returns (out [N, D], aux_loss scalar)."""
    N, D = x.shape
    E = gate_w.shape[1]
    from ..ops.kernels.collective import _axis_size
    ep = 1 if axis_name is None else _axis_size(axis_name)
    e_local = w1.shape[0]
    if e_local * ep != E:
        raise ValueError(
            f"gate has {E} experts but weights hold {e_local} x ep={ep}")

    gates = jax.nn.softmax(x.astype(jnp.float32) @
                           gate_w.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)              # [N]
    prob = jnp.max(gates, axis=-1).astype(x.dtype)       # [N]
    aux = moe_aux_loss(gates, expert_idx)

    C = max(1, int(capacity_factor * N / E))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1)  # 1-based
    keep = pos <= C
    slot = jnp.where(keep, pos - 1, C)  # C = overflow slot, dropped below

    # dispatch: [E, C, D] (scatter drops the overflow slot)
    disp = jnp.zeros((E, C, D), x.dtype)
    disp = disp.at[expert_idx, slot].add(
        jnp.where(keep[:, None], x, 0), mode="drop")

    if axis_name is not None:
        # send each expert shard to its owner: [E, C, D] ->
        # [ep, E_local, C, D]; all_to_all swaps the leading shard axis
        # across devices, so device d ends with its OWN experts' tokens
        # from every peer, stacked along dim 0 -> capacity grows ep-fold
        disp = disp.reshape(ep, e_local, C, D)
        disp = jax.lax.all_to_all(disp, axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
        disp = jnp.swapaxes(disp, 0, 1).reshape(e_local, ep * C, D)

    # batched expert FFN on the MXU: [E_local, cap, D] x [E_local, D, H]
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", disp, w1)
                    + b1[:, None, :])
    out_e = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    if axis_name is not None:
        out_e = jnp.swapaxes(
            out_e.reshape(e_local, ep, C, D), 0, 1)       # [ep, E_l, C, D]
        out_e = jax.lax.all_to_all(out_e, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
        out_e = out_e.reshape(E, C, D)

    # combine: gather each token's slot, scale by its gate prob
    tok = out_e[expert_idx, slot]
    out = jnp.where(keep[:, None], tok, 0) * prob[:, None]
    return out.astype(x.dtype), aux

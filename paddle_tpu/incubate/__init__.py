"""paddle.incubate — pre-stable capability tier (reference
fluid/incubate/): auto-checkpoint elastic recovery."""
from . import checkpoint  # noqa: F401
from . import reader  # noqa: F401

"""Auto-checkpoint — the elastic fault-recovery story.

Reference: /root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py — `AutoCheckpointChecker` (:71) reads
PADDLE_RUNNING_ENV / PADDLE_JOB_ID / PADDLE_EDL_HDFS_CHECKPOINT_PATH;
`train_epoch_range` wraps the epoch loop, checkpointing program state
(persistables + epoch number) under the job id every save interval; the
hook in Executor.run (executor.py:1194) attaches running programs.  On
restart the generator resumes from the last saved epoch.

TPU note: checkpoints are written through the FS abstraction (LocalFS or
HDFSClient per env) — multi-host slices write from rank 0.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from .checkpoint_saver import CheckpointSaver, SerializableBase

__all__ = ["AutoCheckpointChecker", "train_epoch_range", "ExeTrainStatus",
           "_get_train_epoch_range", "_auto_checkpoint"]

g_train_epoch_range = None
g_checker = None


class AutoCheckpointChecker:
    """auto_checkpoint.py:71 parity — env-gated."""

    def __init__(self):
        self._run_env = os.environ.get("PADDLE_RUNNING_ENV")
        self._platform = os.environ.get("PADDLE_RUNNING_PLATFORM", "")
        self._job_id = os.environ.get("PADDLE_JOB_ID")
        self._hdfs_home = os.environ.get("PADDLE_EDL_HDFS_HOME", "")
        self._hdfs_name = os.environ.get("PADDLE_EDL_HDFS_NAME", "")
        self._hdfs_ugi = os.environ.get("PADDLE_EDL_HDFS_UGI", "")
        self._hdfs_ckpt_path = os.environ.get(
            "PADDLE_EDL_HDFS_CHECKPOINT_PATH", "")
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._save_checkpoint_inter = int(os.environ.get(
            "PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))

    def valid(self) -> bool:
        return (self._run_env == "PADDLE_EDL_AUTO_CHECKPOINT"
                and bool(self._job_id)
                and bool(self._hdfs_ckpt_path))

    @property
    def trainer_id(self):
        return self._trainer_id

    @property
    def save_checkpoint_inter(self):
        return self._save_checkpoint_inter

    def get_job_checkpoint_path(self, name) -> str:
        return os.path.join(self._hdfs_ckpt_path, self._job_id, name)

    def make_fs(self):
        if self._hdfs_home and self._hdfs_name:
            from ...distributed.fleet.utils.fs import HDFSClient
            return HDFSClient(self._hdfs_home,
                              {"fs.default.name": self._hdfs_name,
                               "hadoop.job.ugi": self._hdfs_ugi})
        from ...distributed.fleet.utils.fs import LocalFS
        return LocalFS()


def _checker() -> AutoCheckpointChecker:
    global g_checker
    if g_checker is None:
        g_checker = AutoCheckpointChecker()
    return g_checker


class ExeTrainStatus(SerializableBase):
    """auto_checkpoint.py:193 — one (executor, program) training state."""

    def __init__(self, exe=None, program=None, key=None):
        self._exe = exe
        self._program = program
        self._key = key or "default"
        self._epoch_no = -1

    def serialize(self, path):
        os.makedirs(path, exist_ok=True)
        from ...static.executor import global_scope
        from ...static.executor import _persistable_names
        scope = global_scope()
        state = {}
        if self._program is not None:
            for n in _persistable_names(self._program):
                v = scope.get(n)
                if v is not None:
                    state[n] = np.asarray(v)
        np.savez(os.path.join(path, f"{self._key}.npz"), **state)
        with open(os.path.join(path, f"{self._key}.json"), "w") as f:
            json.dump({"epoch_no": self._epoch_no, "key": self._key}, f)

    def deserialize(self, path):
        import jax.numpy as jnp
        from ...static.executor import global_scope
        meta_p = os.path.join(path, f"{self._key}.json")
        if not os.path.exists(meta_p):
            return
        with open(meta_p) as f:
            self._epoch_no = json.load(f)["epoch_no"]
        data = np.load(os.path.join(path, f"{self._key}.npz"))
        scope = global_scope()
        for n in data.files:
            scope.set(n, jnp.asarray(data[n]))


class TrainEpochRange(SerializableBase):
    """auto_checkpoint.py TrainEpochRange: resumable epoch generator."""

    def __init__(self, max_epoch_num, name, checkpoint_inter=None,
                 save_checkpoint=True):
        self._name = name
        self._max_epoch_num = max_epoch_num
        self._checker = _checker()
        self._save_checkpoint = save_checkpoint and self._checker.valid()
        self._inter = (checkpoint_inter
                       if checkpoint_inter is not None
                       else self._checker.save_checkpoint_inter
                       if self._checker.valid() else 0)
        self._last_ckpt_time = time.time()
        self._exe_statuses: Dict[str, ExeTrainStatus] = {}
        self._start_epoch = 0
        self._epoch_no = -1
        self._restore_dir = None  # newest checkpoint's obj_0 dir
        if self._save_checkpoint:
            self._fs = self._checker.make_fs()
            self._saver = CheckpointSaver(self._fs)
            self._path = self._checker.get_job_checkpoint_path(name)
            # load_checkpoint verifies integrity and may fall back to an
            # earlier number than the newest dir — trust ITS return value
            no = self._saver.load_checkpoint(self._path, [self])
            if no is not None:
                self._start_epoch = self._epoch_no + 1
                # statuses restore lazily at _attach (the programs don't
                # exist yet); the saver reports the exact (absolute) local
                # dir it verified and deserialized from — on a remote FS
                # that's the materialized cache copy, never the remote path
                self._restore_dir = os.path.join(
                    self._saver.last_restore_dir, "obj_0")

    @property
    def name(self):
        return self._name

    def get(self):
        """The resumable epoch iterator."""
        global g_train_epoch_range
        g_train_epoch_range = self
        try:
            for epoch in range(self._start_epoch, self._max_epoch_num):
                self._epoch_no = epoch
                yield epoch
                self._maybe_save(epoch)
        finally:
            g_train_epoch_range = None

    def _maybe_save(self, epoch, force=False):
        if not self._save_checkpoint:
            return
        now = time.time()
        if not force and (now - self._last_ckpt_time) < self._inter:
            return
        # serialize() writes the attached ExeTrainStatus blobs too
        self._saver.save_checkpoint(self._path, [self],
                                    trainer_id=self._checker.trainer_id)
        self._last_ckpt_time = now

    def save_checkpoint(self):
        self._maybe_save(self._epoch_no, force=True)

    # -- SerializableBase (epoch-range metadata) ----------------------------
    def serialize(self, path):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "range.json"), "w") as f:
            json.dump({"name": self._name, "epoch_no": self._epoch_no,
                       "max_epoch_num": self._max_epoch_num}, f)
        for s in self._exe_statuses.values():
            s.serialize(path)

    def deserialize(self, path):
        with open(os.path.join(path, "range.json")) as f:
            d = json.load(f)
        self._epoch_no = d["epoch_no"]

    def _attach(self, exe, program):
        # stable across restarts (id(exe) is not): keyed by program
        key = f"exe_{program.fingerprint()[:12]}"
        if key not in self._exe_statuses:
            st = ExeTrainStatus(exe, program, key)
            self._exe_statuses[key] = st
            if self._restore_dir is not None:
                # resume: overwrite freshly initialized persistables with
                # the checkpointed weights
                st.deserialize(self._restore_dir)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None):
    """auto_checkpoint.py train_epoch_range — resumes after restart."""
    r = TrainEpochRange(max_epoch_num, "train_epoch_range",
                        checkpoint_inter=save_checkpoint_inter)
    yield from r.get()


def _get_train_epoch_range():
    return g_train_epoch_range


def _auto_checkpoint(exe, program):
    """Executor.run hook (reference executor.py:1194): attach the running
    (exe, program) to the active epoch range so its persistables are part
    of the checkpoint."""
    r = _get_train_epoch_range()
    if r is None or not _checker().valid():
        return
    from ...core.program import Program
    if isinstance(program, Program):
        r._attach(exe, program)

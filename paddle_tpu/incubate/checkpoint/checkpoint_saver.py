"""Numbered checkpoint management (fluid-parity surface, real engine).

Reference: /root/reference/python/paddle/fluid/incubate/checkpoint/
checkpoint_saver.py — CheckpointSaver over an FS abstraction (HDFS in
production, local in tests): save_checkpoint writes checkpoint.<n>,
load_checkpoint restores the newest, older ones are pruned.

Re-based on paddle_tpu/checkpoint's atomic commit protocol: objects are
serialized into a dot-prefixed staging dir, every written file is
fsync'd and inventoried (size + CRC-32) in ``_meta.json``, and the dir
is atomically renamed to its numbered name.  ``get_last_checkpoint_no``
counts only committed checkpoints (meta present); ``load_checkpoint``
verifies the inventory first and falls back to the previous number when
the newest is truncated or bit-flipped — same keep/prune env contract
as before, no silently-corrupt restores.
"""
from __future__ import annotations

import json
import os
import warnings
from typing import Optional

from ...checkpoint.atomic import (commit_dir, crc32_file, fsync_path,
                                  new_temp_path, sweep_dead_stages)
from ...core.monitor import stat_add
from ...distributed.fleet.utils.fs import FS, LocalFS

__all__ = ["SerializableBase", "CheckpointSaver"]

_META = "_meta.json"


class SerializableBase:
    def serialize(self, path):
        raise NotImplementedError

    def deserialize(self, path):
        raise NotImplementedError


class CheckpointSaver:
    def __init__(self, fs: Optional[FS] = None):
        self._fs = fs or LocalFS()
        self._is_local = isinstance(self._fs, LocalFS)
        # absolute path of the checkpoint dir the last successful
        # load_checkpoint() deserialized from (the local cache copy for
        # remote FSes) — callers doing lazy/deferred restores read from
        # here instead of re-deriving cache paths
        self.last_restore_dir: Optional[str] = None

    def _ckpt_dirs(self, root, committed_only=True):
        if not self._fs.is_exist(root):
            return []
        dirs, _ = self._fs.ls_dir(root)
        nums = []
        for d in dirs:
            if d.startswith("__paddle_checkpoint__"):
                try:
                    no = int(d.rsplit(".", 1)[-1])
                except ValueError:
                    continue
                if committed_only and not self._fs.is_exist(
                        os.path.join(root, d, _META)):
                    continue  # uncommitted/legacy partial dir
                nums.append(no)
        return sorted(nums)

    def get_last_checkpoint_no(self, root) -> int:
        nums = self._ckpt_dirs(root)
        return nums[-1] if nums else -1

    def _inventory(self, d, fsync=False):
        """{relpath: {size, crc32}} over every file under `d` (meta
        excluded) — the integrity line load_checkpoint verifies.  The CRC
        read is inherent (objects serialize their own files, so the bytes
        only exist on disk); with `fsync` the same walk also persists each
        file so commit_dir need not walk a second time."""
        inv = {}
        for dirpath, _dirs, files in os.walk(d):
            for name in files:
                p = os.path.join(dirpath, name)
                rel = os.path.relpath(p, d)
                if rel == _META:
                    continue
                inv[rel] = {"size": os.path.getsize(p),
                            "crc32": crc32_file(p)}
                if fsync:
                    fsync_path(p)
        return inv

    def _materialize(self, d, local_cache_path):
        """A LOCAL directory holding checkpoint `d`'s contents: `d` itself
        on LocalFS; a download into the local cache for remote FSes
        (objects serialize/deserialize against local paths, as in the
        reference's HDFS flow)."""
        if self._is_local:
            return d
        import shutil
        local = os.path.join(local_cache_path, os.path.basename(d))
        shutil.rmtree(local, ignore_errors=True)
        os.makedirs(local_cache_path, exist_ok=True)
        self._fs.download(d, local_cache_path)
        return local

    def _verify(self, d, local_cache_path=".cache"):
        """Integrity screen; returns the VERIFIED LOCAL dir, or None."""
        try:
            local = self._materialize(d, local_cache_path)
        except Exception:  # noqa: BLE001 - remote fetch failure = invalid
            return None
        meta_p = os.path.join(local, _META)
        try:
            with open(meta_p) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        for rel, want in meta.get("files", {}).items():
            p = os.path.join(local, rel)
            try:
                if os.path.getsize(p) != want["size"] or \
                        crc32_file(p) != want["crc32"]:
                    return None
            except OSError:
                return None
        return local

    def save_checkpoint(self, path, slists, trainer_id=None,
                        local_cache_path=".cache", max_keep=3) -> int:
        """Serialize each object into the next numbered checkpoint dir —
        staged locally, fsync'd, CRC-inventoried, then committed: an
        atomic rename on LocalFS, a stage-then-upload through the FS
        client for remote filesystems."""
        self._fs.mkdirs(path)
        # drop stage dirs a crashed/preempted save abandoned — on a pod
        # that restarts repeatedly they would otherwise pile up unboundedly
        stage_home = path if self._is_local else local_cache_path
        sweep_dead_stages(stage_home, ".tmp.__paddle_checkpoint__")
        # next number counts UNcommitted dirs too, so a crashed save never
        # gets silently overwritten by the next one reusing its number
        all_nums = self._ckpt_dirs(path, committed_only=False)
        no = (all_nums[-1] if all_nums else -1) + 1
        final = os.path.join(path, f"__paddle_checkpoint__.{no}")
        if self._is_local:
            stage = new_temp_path(final)
        else:
            os.makedirs(local_cache_path, exist_ok=True)
            stage = new_temp_path(os.path.join(
                local_cache_path, os.path.basename(final)))
        os.makedirs(stage)
        for i, s in enumerate(slists):
            s.serialize(os.path.join(stage, f"obj_{i}"))
        meta = {"no": no, "n_objs": len(slists), "trainer_id": trainer_id,
                "files": self._inventory(stage, fsync=self._is_local)}
        with open(os.path.join(stage, _META), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        if self._is_local:
            # files fsync'd in _inventory; still persist the staged dir's
            # entries and (after publishing) the rename itself
            fsync_path(stage)
            commit_dir(stage, final, fsync=False)
            fsync_path(path)
        else:
            import shutil
            self._fs.upload(stage, final)
            shutil.rmtree(stage, ignore_errors=True)
        stat_add("checkpoint.saver_commits")
        self.clean_redundant_checkpoints(path, max_keep)
        return no

    def load_checkpoint(self, path, slists, trainer_id=None,
                        checkpoint_no=None, local_cache_path=".cache"):
        """Restore the newest VERIFIED checkpoint (or exactly
        `checkpoint_no`).  A checkpoint failing its CRC inventory is
        skipped with a warning and the previous number is tried."""
        if checkpoint_no is not None:
            d = os.path.join(path, f"__paddle_checkpoint__.{checkpoint_no}")
            local = self._verify(d, local_cache_path)
            if local is None:
                raise RuntimeError(
                    f"checkpoint {d} is missing, truncated, or corrupt")
            self.last_restore_dir = os.path.abspath(local)
            for i, s in enumerate(slists):
                s.deserialize(os.path.join(local, f"obj_{i}"))
            return checkpoint_no
        for no in reversed(self._ckpt_dirs(path)):
            d = os.path.join(path, f"__paddle_checkpoint__.{no}")
            local = self._verify(d, local_cache_path)
            if local is None:
                stat_add("checkpoint.load_fallbacks")
                warnings.warn(
                    f"checkpoint {d} failed integrity verification; "
                    "falling back to the previous checkpoint",
                    RuntimeWarning, stacklevel=2)
                continue
            self.last_restore_dir = os.path.abspath(local)
            for i, s in enumerate(slists):
                s.deserialize(os.path.join(local, f"obj_{i}"))
            return no
        return None

    def clean_redundant_checkpoints(self, root, max_keep=3):
        nums = self._ckpt_dirs(root)
        for n in nums[:-max_keep]:
            self._fs.delete(os.path.join(
                root, f"__paddle_checkpoint__.{n}"))

"""Numbered checkpoint management.

Reference: /root/reference/python/paddle/fluid/incubate/checkpoint/
checkpoint_saver.py — CheckpointSaver over an FS abstraction (HDFS in
production, local in tests): save_checkpoint writes checkpoint.<n>,
load_checkpoint restores the newest, older ones are pruned.
"""
from __future__ import annotations

import json
import os
from typing import Optional

from ...distributed.fleet.utils.fs import FS, LocalFS

__all__ = ["SerializableBase", "CheckpointSaver"]


class SerializableBase:
    def serialize(self, path):
        raise NotImplementedError

    def deserialize(self, path):
        raise NotImplementedError


class CheckpointSaver:
    def __init__(self, fs: Optional[FS] = None):
        self._fs = fs or LocalFS()

    def _ckpt_dirs(self, root):
        if not self._fs.is_exist(root):
            return []
        dirs, _ = self._fs.ls_dir(root)
        nums = []
        for d in dirs:
            if d.startswith("__paddle_checkpoint__"):
                try:
                    nums.append(int(d.rsplit(".", 1)[-1]))
                except ValueError:
                    continue
        return sorted(nums)

    def get_last_checkpoint_no(self, root) -> int:
        nums = self._ckpt_dirs(root)
        return nums[-1] if nums else -1

    def save_checkpoint(self, path, slists, trainer_id=None,
                        local_cache_path=".cache", max_keep=3) -> int:
        """Serialize each object into the next numbered checkpoint dir."""
        no = self.get_last_checkpoint_no(path) + 1
        d = os.path.join(path, f"__paddle_checkpoint__.{no}")
        self._fs.mkdirs(d)
        for i, s in enumerate(slists):
            s.serialize(os.path.join(d, f"obj_{i}"))
        with open(os.path.join(d, "_meta.json"), "w") as f:
            json.dump({"no": no, "n_objs": len(slists),
                       "trainer_id": trainer_id}, f)
        self.clean_redundant_checkpoints(path, max_keep)
        return no

    def load_checkpoint(self, path, slists, trainer_id=None,
                        checkpoint_no=None, local_cache_path=".cache"):
        if checkpoint_no is None:
            checkpoint_no = self.get_last_checkpoint_no(path)
        if checkpoint_no < 0:
            return None
        d = os.path.join(path, f"__paddle_checkpoint__.{checkpoint_no}")
        for i, s in enumerate(slists):
            s.deserialize(os.path.join(d, f"obj_{i}"))
        return checkpoint_no

    def clean_redundant_checkpoints(self, root, max_keep=3):
        nums = self._ckpt_dirs(root)
        for n in nums[:-max_keep]:
            self._fs.delete(os.path.join(
                root, f"__paddle_checkpoint__.{n}"))

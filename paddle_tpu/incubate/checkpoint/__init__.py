from .checkpoint_saver import CheckpointSaver, SerializableBase  # noqa: F401
from . import auto_checkpoint  # noqa: F401

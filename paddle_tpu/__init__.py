"""paddle_tpu: a TPU-native deep-learning framework with Paddle-Fluid-era
capabilities, built on JAX/XLA/pjit/Pallas.

The public API mirrors paddle 2.0 (`paddle.*`) plus the fluid static-graph
API (`paddle_tpu.static`, analog of `paddle.fluid`).  See SURVEY.md for the
capability inventory this package implements.
"""
from .core.dtype import DataType as dtype  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, XLAPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    device_count,
)
from .core.program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    name_scope,
)
from .core.generator import seed  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.monitor import stat_add, stat_get, all_stats  # noqa: F401

# kernel library registers all ops on import
from .ops import kernels as _kernels  # noqa: F401

__version__ = "0.1.0"


def _setup_api():
    """Populate the 2.0-style public namespace lazily as subpackages land."""
    import importlib
    for mod in ("dygraph", "tensor", "nn", "optimizer", "static",
                "distributed", "amp", "metric", "io", "vision", "text",
                "hapi", "jit", "incubate", "profiler", "utils", "slim",
                "reader", "dataset", "fluid", "regularizer"):
        try:
            importlib.import_module(f".{mod}", __name__)
        except ImportError:
            continue


_setup_api()

# promote common symbols
from .dygraph.base import (  # noqa: F401
    enable_static, disable_static, in_dynamic_mode, in_dygraph_mode, no_grad,
    set_grad_enabled, is_grad_enabled,
)
from .dygraph.tensor import Tensor, to_tensor  # noqa: F401
from .dygraph.engine import grad  # noqa: F401
from .dygraph.layers import ParamBase  # noqa: F401

try:
    from .tensor import *  # noqa: F401,F403
except ImportError:
    pass
try:
    from .hapi.model import Model, Input  # noqa: F401
except ImportError:
    pass
try:
    from .io.framework_io import save, load  # noqa: F401
except ImportError:
    pass
from .batch import batch  # noqa: F401

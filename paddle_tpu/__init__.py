"""paddle_tpu: a TPU-native deep-learning framework with Paddle-Fluid-era
capabilities, built on JAX/XLA/pjit/Pallas.

The public API mirrors paddle 2.0 (`paddle.*`) plus the fluid static-graph
API (`paddle_tpu.static`, analog of `paddle.fluid`).  See SURVEY.md for the
capability inventory this package implements.
"""
from .core.dtype import DataType as dtype  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, XLAPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    device_count,
)
from .core.program import (  # noqa: F401
    Program, program_guard, default_main_program, default_startup_program,
    name_scope,
)
from .core.generator import seed  # noqa: F401
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.monitor import stat_add, stat_get, all_stats  # noqa: F401

# kernel library registers all ops on import
from .ops import kernels as _kernels  # noqa: F401

__version__ = "0.1.0"


def _setup_api():
    """Populate the 2.0-style public namespace lazily as subpackages land."""
    import importlib
    for mod in ("dygraph", "tensor", "nn", "optimizer", "static",
                "distributed", "amp", "metric", "io", "vision", "text",
                "hapi", "jit", "incubate", "profiler", "utils", "slim",
                "reader", "dataset", "fluid", "regularizer",
                "distribution", "compat", "sysconfig", "framework",
                "serving", "checkpoint", "observability"):
        try:
            importlib.import_module(f".{mod}", __name__)
        except ImportError:
            continue


_setup_api()

# promote common symbols
from .dygraph.base import (  # noqa: F401
    enable_static, disable_static, in_dynamic_mode, in_dygraph_mode, no_grad,
    set_grad_enabled, is_grad_enabled,
)
from .dygraph.tensor import Tensor, to_tensor  # noqa: F401
from .dygraph.engine import grad  # noqa: F401
from .dygraph.layers import ParamBase  # noqa: F401

try:
    from .tensor import *  # noqa: F401,F403
except ImportError:
    pass
try:
    from .hapi.model import Model, Input  # noqa: F401
except ImportError:
    pass
try:
    from .io.framework_io import save, load  # noqa: F401
except ImportError:
    pass
from .batch import batch  # noqa: F401

# -- 2.0-alpha top-level surface (reference python/paddle/__init__.py) ------
from .tensor.compat import *  # noqa: F401,F403
from .core.dtype import get_default_dtype, set_default_dtype  # noqa: F401
from .core.generator import seed as manual_seed  # noqa: F401
from .core.program import VarDesc as Variable  # noqa: F401
from .static.param_attr import ParamAttr  # noqa: F401
from .optimizer.lr_scheduler import (  # noqa: F401
    NoamDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay,
)
from .distributed.parallel import DataParallel  # noqa: F401
from .core.place import XLAPlace as XPUPlace  # noqa: F401

# LoD containers: ragged sequences are padded+lengths here (io/bucketing
# is the documented redesign); the NAMES alias the eager tensor / a list
# so isinstance checks in ported code keep working.
LoDTensor = Tensor
LoDTensorArray = list


try:
    from .jit import SaveLoadConfig  # noqa: F401
except ImportError:  # jit is in _setup_api's tolerant list
    pass


def get_cuda_rng_state():
    """Parity shim: the RNG is the stateless fold_in generator
    (core/generator.py); returns its seed state."""
    from .core.generator import global_seed
    return [global_seed()]


def set_cuda_rng_state(state):
    from .core.generator import seed as _set_seed
    if state:
        _set_seed(int(state[0]))

"""Fault-injection harness — make host loss and torn writes reproducible.

The elastic tier's whole claim is "training survives rank death, mesh
shrink and torn checkpoints"; none of that is testable in CI unless the
faults themselves are deterministic.  This module injects them from an
env knob, so the SAME kill/shrink/regrow scenario replays on the
8-device CPU mesh in every run (tests/test_elastic.py, tools/
elastic_smoke.py) and on a real preemptible fleet when needed.

``PADDLE_TPU_CHAOS`` grammar — semicolon-separated directives:

  ``kill@<step>[:rank=<r>][:signal=kill|term]``
      Kill THIS process (default SIGKILL — a preempted host gets no
      goodbye; ``signal=term`` simulates a graceful preemption notice)
      right after the executor finishes micro-step ``<step>``, but only
      on trainer rank ``<r>`` (default 0, from ``PADDLE_TRAINER_ID``).

  ``lose_host@<step>[:host=<h>]``
      Simulate losing a WHOLE host of the fleet (docs/elastic.md
      multi-host): right after the executor finishes micro-step
      ``<step>``, but only when this process's fleet host id
      (``PADDLE_TPU_FLEET_HOST_ID``) is ``<h>`` (default 0), SIGKILL
      the host's fleet launcher (``PADDLE_TPU_FLEET_LAUNCHER_PID``)
      and then this trainer — no goodbye from either, exactly what a
      preempted host looks like.  Surviving hosts' controllers see the
      membership record go stale and drive the cross-host re-form.

  ``slow_save=<seconds>``
      Sleep inside the checkpoint writer between the shard bytes and the
      manifest — the slow-disk half of a torn-write race.

  ``torn_save@<step>``
      SIGKILL the process mid-checkpoint-write at save step ``<step>``
      (shard bytes staged, manifest/commit never happens).  Exercises
      the crash-consistency contract: the orphaned stage is swept on the
      next startup and load() falls back to the last CRC-valid commit.

  ``collective_fail@<step>[:times=<n>][:rank=<r>]``
      Raise ``ChaosCollectiveError`` from the next ``<n>`` (default 1)
      compiled-program dispatches at executor step ``<step>`` — the
      transient collective failure a flaky ICI link produces; callers
      retry or surface it to the supervisor.  ``rank=<r>`` restricts the
      fault to one trainer rank (default: every rank); ``times`` large
      enough to outlast any retry budget turns the fault PERMANENT — the
      wedged-rank scenario the heartbeat stall deadline exists for
      (docs/observability.md).

Every fired directive is also recorded in the run journal
(``paddle_tpu.observability.journal``) when journaling is armed, so a
chaos run's post-mortem shows which faults actually fired where.

Hooks are wired into ``Executor.run`` (step_hook), ``CheckpointManager.
_persist`` (save_hook) and ``CompiledProgram._run`` (collective_hook);
each is a no-op costing one attribute read when chaos is off.
"""
from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

__all__ = ["ChaosCollectiveError", "enabled", "reload", "step_hook",
           "save_hook", "collective_hook", "CHAOS_ENV"]

CHAOS_ENV = "PADDLE_TPU_CHAOS"


class ChaosCollectiveError(RuntimeError):
    """Injected transient collective failure (retryable)."""


class _Directive:
    __slots__ = ("kind", "step", "rank", "sig", "seconds", "times")

    def __init__(self, kind, step=None, rank=0, sig=signal.SIGKILL,
                 seconds=0.0, times=1):
        self.kind = kind
        self.step = step
        self.rank = rank
        self.sig = sig
        self.seconds = seconds
        self.times = times


_spec: Optional[List[_Directive]] = None
_spec_raw: Optional[str] = None


def _rank() -> int:
    # the observability tier's shared resolver, so the chaos rank filter,
    # heartbeat filenames and journal rank field can never disagree
    from ..observability.journal import trainer_rank
    return trainer_rank()


def _parse(raw: str) -> List[_Directive]:
    out = []
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        head = fields[0]
        opts = {}
        for f in fields[1:]:
            k, _, v = f.partition("=")
            opts[k.strip()] = v.strip()
        if "@" in head:
            name, _, at = head.partition("@")
            name = name.strip()
            step = int(at)
        else:
            name, _, val = head.partition("=")
            name = name.strip()
            step = None
            if val:
                opts["value"] = val.strip()
        if name == "kill":
            sig = signal.SIGTERM if \
                opts.get("signal", "kill").lower() == "term" \
                else signal.SIGKILL
            out.append(_Directive("kill", step=step,
                                  rank=int(opts.get("rank", 0)), sig=sig))
        elif name == "lose_host":
            out.append(_Directive("lose_host", step=step,
                                  rank=int(opts.get("host", 0))))
        elif name == "slow_save":
            out.append(_Directive("slow_save",
                                  seconds=float(opts.get("value", 0.1))))
        elif name == "torn_save":
            out.append(_Directive("torn_save", step=step,
                                  rank=int(opts.get("rank", 0))))
        elif name == "collective_fail":
            out.append(_Directive("collective_fail", step=step,
                                  times=int(opts.get("times", 1)),
                                  rank=(int(opts["rank"])
                                        if "rank" in opts else None)))
        else:
            raise ValueError(
                f"unknown {CHAOS_ENV} directive {part!r} (see "
                "paddle_tpu/testing/chaos.py for the grammar)")
    return out


def reload() -> None:
    """Re-parse ``PADDLE_TPU_CHAOS`` (tests monkeypatching the env call
    this; normal processes parse once at first use)."""
    global _spec, _spec_raw
    _spec_raw = os.environ.get(CHAOS_ENV, "")
    _spec = _parse(_spec_raw) if _spec_raw else []


def _directives() -> List[_Directive]:
    if _spec is None or _spec_raw != os.environ.get(CHAOS_ENV, ""):
        reload()
    return _spec


def enabled() -> bool:
    return bool(os.environ.get(CHAOS_ENV)) and bool(_directives())


def _die(sig) -> None:  # pragma: no cover - ends the process
    # flush whatever the harness buffered; SIGKILL gives no second chance
    try:
        import sys
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    os.kill(os.getpid(), sig)
    if sig != signal.SIGKILL:
        # a SIGTERM handler (preemption save) may return; don't continue
        # training afterwards — the "host" is gone
        os._exit(143)


def _journal_fire(directive: str, step) -> None:
    """Record a fired directive in the run journal (no-op when
    journaling is unarmed; flushed per line, so even a SIGKILL directive
    leaves its own record behind)."""
    try:
        from ..observability.journal import emit
        emit("chaos", directive=directive, step=step)
    except Exception:
        pass


def _fleet_host() -> int:
    try:
        return int(os.environ.get("PADDLE_TPU_FLEET_HOST_ID", "0"))
    except ValueError:
        return 0


def step_hook(step: int) -> None:
    """Called by the executor after finishing micro-step `step`."""
    if not enabled():
        return
    for d in _directives():
        if d.kind == "kill" and d.step == step and d.rank == _rank():
            d.step = None  # never double-fire in one process
            _journal_fire("kill", step)
            _die(d.sig)
        elif d.kind == "lose_host" and d.step == step and \
                d.rank == _fleet_host():
            d.step = None
            _journal_fire("lose_host", step)
            # the launcher first (it must not observe our death and
            # relaunch locally — the HOST is gone), then ourselves;
            # SIGKILL both: a preempted host sends no goodbyes
            pid = os.environ.get("PADDLE_TPU_FLEET_LAUNCHER_PID")
            if pid:
                try:
                    os.kill(int(pid), signal.SIGKILL)
                except (ValueError, ProcessLookupError, PermissionError):
                    pass
            _die(signal.SIGKILL)


def save_hook(stage_dir: str, step: int) -> None:
    """Called by the checkpoint writer with the shard bytes staged but
    the manifest/commit not yet written."""
    if not enabled():
        return
    for d in _directives():
        if d.kind == "slow_save" and d.seconds > 0:
            _journal_fire("slow_save", step)
            time.sleep(d.seconds)
        elif d.kind == "torn_save" and d.step == step and \
                d.rank == _rank():
            d.step = None
            _journal_fire("torn_save", step)
            _die(signal.SIGKILL)


def collective_hook(step: int) -> None:
    """Called before each compiled-program dispatch; raises the injected
    transient failure while its budget lasts."""
    if not enabled():
        return
    for d in _directives():
        if d.kind == "collective_fail" and d.step == step and \
                d.times > 0 and d.rank in (None, _rank()):
            d.times -= 1
            _journal_fire("collective_fail", step)
            raise ChaosCollectiveError(
                f"injected transient collective failure at step {step} "
                f"({d.times} more)")

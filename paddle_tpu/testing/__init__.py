"""paddle_tpu.testing — fault-injection and test harness utilities."""
from . import chaos  # noqa: F401

__all__ = ["chaos"]

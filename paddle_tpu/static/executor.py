"""Executor: runs a Program by tracing its whole block into ONE jitted XLA
computation.

Analog of the reference executor stack
(/root/reference/python/paddle/fluid/executor.py:474 Executor,
 /root/reference/paddle/fluid/framework/executor.cc:474-480 per-op hot loop) —
but where the reference interprets op-by-op with per-kernel launches, here the
op list is composed into a single function (state, feed, seed) ->
(fetches, state') and `jax.jit`-ed with state buffers donated, so XLA fuses
the entire step (SURVEY.md §3.1 "the whole :474-480 loop becomes ONE traced
XLA computation").  Garbage collection (executor.cc:445-472 GC selection)
disappears: XLA buffer liveness subsumes it.

Startup programs are interpreted eagerly op-by-op — they run once, tracing
would only add compile latency.  Set FLAGS_eager_run=1 to interpret main
programs too (debug path, analog of the reference's sequential executor).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.program import Program, Block, default_main_program, OpRole
from ..core.place import CPUPlace, XLAPlace, Place, _current_expected_place
from ..core.dtype import np_dtype
from ..ops.registry import get_op_info, OpContext

__all__ = ["Executor", "Scope", "global_scope", "scope_guard",
           "as_numpy", "BlockTracer"]


class Scope:
    """name -> device array store (analog of framework/scope.h:52, flattened:
    no parent chain — programs here use unique names)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def find_var(self, name: str):
        return _VarView(self, name) if name in self.vars else None

    def var(self, name: str):
        self.vars.setdefault(name, None)
        return _VarView(self, name)

    def set(self, name: str, value):
        self.vars[name] = value

    def get(self, name: str):
        return self.vars.get(name)

    def drop_kids(self):
        pass

    def keys(self):
        return self.vars.keys()


class _VarView:
    def __init__(self, scope, name):
        self._scope, self._name = scope, name

    def get_tensor(self):
        return self._scope.vars[self._name]

    def set(self, value, place=None):
        self._scope.vars[self._name] = jnp.asarray(value)


_global_scope = Scope()
_scope_stack = threading.local()


def global_scope() -> Scope:
    stack = getattr(_scope_stack, "stack", None)
    return stack[-1] if stack else _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        if not hasattr(_scope_stack, "stack"):
            _scope_stack.stack = []
        _scope_stack.stack.append(self.scope)
        return self.scope

    def __exit__(self, *a):
        _scope_stack.stack.pop()


def as_numpy(x):
    if isinstance(x, (list, tuple)):
        return [as_numpy(i) for i in x]
    return np.asarray(x)


# ---------------------------------------------------------------------------
# block tracing
# ---------------------------------------------------------------------------
class BlockTracer:
    """Composes a block's ops into one pure function over an environment of
    jax values.  Shared by Executor (jit path), the startup interpreter, and
    the distributed CompiledProgram (which traces under shard_map)."""

    def __init__(self, block: Block, skip_types=("feed", "fetch")):
        self.block = block
        self.skip_types = set(skip_types)

    def run(self, env: Dict[str, Any], ctx: OpContext,
            ops=None, on_op=None) -> Dict[str, Any]:
        for op in (ops if ops is not None else self.block.ops):
            if op.type in self.skip_types:
                continue
            self.run_op(op, env, ctx)
            if on_op is not None:
                on_op(op, env)
        return env

    def run_op(self, op, env: Dict[str, Any], ctx: OpContext):
        # sub-block ops (while/cond/static_rnn/...) reach their Program
        # through the context and recurse with their own BlockTracer
        ctx.program = self.block.program
        info = get_op_info(op.type)
        if info is None:
            raise NotImplementedError(
                f"op {op.type!r} has no registered kernel")
        ins: Dict[str, Any] = {}
        for slot in info.inputs:
            names = op.inputs.get(slot.name, [])
            if slot.duplicable:
                if slot.name.endswith("@GRAD"):
                    # cotangent lists must stay POSITION-ALIGNED with the
                    # forward output slot — an absent grad ('' name, e.g.
                    # a while's non-differentiable carried cond) is None,
                    # not dropped, or every grad after it lands on the
                    # wrong output
                    ins[slot.name] = [env.get(n) if n else None
                                      for n in names]
                else:
                    ins[slot.name] = [env[n] for n in names
                                      if n and n in env]
            else:
                n = names[0] if names else None
                ins[slot.name] = env.get(n) if n else None
        attrs = dict(op.attrs)
        outs = info.kernel(ins, attrs, ctx)
        for slot in info.outputs:
            names = op.outputs.get(slot.name, [])
            if not names:
                continue
            val = outs.get(slot.name) if outs else None
            if val is None:
                continue
            if slot.duplicable:
                for n, v in zip(names, val):
                    if n and v is not None:
                        env[n] = v
            else:
                if names[0]:
                    env[names[0]] = val
        return env


def _persistable_names(program: Program) -> List[str]:
    return sorted(v.name for b in program.blocks for v in b.vars.values()
                  if v.persistable)


class Executor:
    """exe = Executor(XLAPlace(0)); exe.run(startup); exe.run(main, feed,
    fetch_list) — the reference's two-program contract (executor.py:474)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or _current_expected_place()
        # compiled step cache: key -> (jitted fn, state names)
        self._cache: Dict[Tuple, Any] = {}
        self._step = 0

    # -- public API ---------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True, fetch_var_name="fetch",
            feed_var_name="feed", use_prune=False):
        from ..distributed.compiled_program import CompiledProgram
        if isinstance(program, CompiledProgram) or (
                program is not None and not isinstance(program, Program)
                and hasattr(program, "_run")):
            # CompiledProgram / Pipeline / PS trainer program dispatch
            return program._run(self, feed, fetch_list, scope, return_numpy)
        if getattr(program, "_ps_server_config", None):
            # pserver program: exe.run(pserver_prog) == listen_and_serv
            from ..distributed.ps.kv_server import KVServer
            cfg = program._ps_server_config
            server = KVServer(cfg["endpoint"],
                              num_trainers=cfg.get("num_trainers", 1))
            server.serve()  # blocks until a SHUTDOWN rpc
            return []
        program = program if program is not None else default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in (fetch_list or [])]

        if self._program_is_startup(program):
            self._run_eager(program, scope, feed, fetch_names)
            return [] if not fetch_names else [
                as_numpy(scope.get(n)) if return_numpy else scope.get(n)
                for n in fetch_names]

        # elastic auto-checkpoint hook (reference executor.py:1194)
        from ..incubate.checkpoint.auto_checkpoint import _auto_checkpoint
        _auto_checkpoint(self, program)

        from ..core.flags import flag
        from ..core.monitor import stat_add
        from ..profiler import RecordEvent
        stat_add("executor_run_times")
        with RecordEvent("Executor::Run"):
            if flag("eager_run", False):
                self._run_eager(program, scope, feed, fetch_names)
                fetched = [scope.get(n) for n in fetch_names]
                results = [as_numpy(f) for f in fetched] \
                    if return_numpy else fetched
            else:
                results = self._run_compiled(program, scope, feed,
                                             fetch_names, return_numpy)
        if flag("check_nan_inf", False):
            self._check_nan_inf(fetch_names, results, scope)
        return results

    def _check_nan_inf(self, fetch_names, results, scope):
        """FLAGS_check_nan_inf (reference details/nan_inf_utils_detail —
        per-op output scan; here: fetches + persistable state after the
        jitted step, which bounds the same failure)."""
        bad = []
        for n, v in zip(fetch_names, results or []):
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                bad.append(f"fetch {n!r}")
        for n in scope.keys():
            v = scope.get(n)
            if v is None:
                continue
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                bad.append(f"var {n!r}")
        if bad:
            raise RuntimeError(
                "FLAGS_check_nan_inf: non-finite values in "
                + ", ".join(bad))

    # -- dataset-driven training (MultiTrainer path, executor.py:1345) ------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        from ..distributed.dataset import run_from_dataset
        from ..core.program import default_main_program
        if fetch_handler is not None:
            raise NotImplementedError(
                "fetch_handler callbacks are not supported; poll "
                "fetch_list/print_period instead")
        program = program if program is not None else default_main_program()
        if thread:
            dataset.set_thread(thread)
        return run_from_dataset(self, program, dataset, scope,
                                fetch_list, fetch_info, print_period, debug)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Like train_from_dataset but guaranteed side-effect-free on the
        parameters (reference executor.py:1476 contract): training-role
        ops (backward/optimizer/lr-sched) are stripped and test mode is
        applied before running."""
        from ..core.program import default_main_program, OpRole
        program = program if program is not None else default_main_program()
        infer = program.clone(for_test=True)
        blk = infer.global_block()
        train_roles = (OpRole.Backward, OpRole.Optimize, OpRole.LRSched,
                       OpRole.Optimize | OpRole.LRSched)
        blk.ops = [op for op in blk.ops
                   if op.attrs.get(OpRole.KEY, OpRole.Forward)
                   not in train_roles]
        return self.train_from_dataset(infer, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _per_op_nan_scan(op, env):
        """Eager-mode per-op output scan under FLAGS_check_nan_inf — names
        the op that produced the first non-finite value (reference
        details/nan_inf_utils_detail.cc CheckOpHasNanOrInf)."""
        for n in op.output_names():
            v = env.get(n)
            if v is None or not hasattr(v, "dtype"):
                continue
            if jnp.issubdtype(v.dtype, jnp.floating) and \
                    not bool(jnp.isfinite(v).all()):
                raise RuntimeError(
                    f"FLAGS_check_nan_inf: op {op.type!r} produced "
                    f"non-finite values in output {n!r}")

    def close(self):
        self._cache.clear()

    # -- eager interpreter (startup / debug) --------------------------------
    def _program_is_startup(self, program: Program) -> bool:
        """Explicit two-program contract: program_guard / the default-program
        registry stamp `_role` ("startup" runs eagerly once, "main" takes the
        jit+donate path).  Unmarked programs (hand-built, deserialized) fall
        back to the init-op heuristic."""
        if program._role is not None:
            return program._role == "startup"
        b = program.global_block()
        init_types = {"fill_constant", "uniform_random", "gaussian_random",
                      "truncated_gaussian_random", "assign_value", "eye",
                      "c_broadcast", "broadcast", "seed", "range", "linspace"}
        return len(b.ops) > 0 and all(op.type in init_types for op in b.ops)

    def _run_eager(self, program: Program, scope: Scope, feed, fetch_names):
        from ..core.flags import flag
        block = program.global_block()
        env = {k: v for k, v in scope.vars.items() if v is not None}
        for name, val in feed.items():
            env[name] = self._coerce_feed(block, name, val)
        ctx = OpContext(seed=self._seed_for_step(program))
        on_op = self._per_op_nan_scan if flag("check_nan_inf", False) else None
        BlockTracer(block).run(env, ctx, on_op=on_op)
        self._step += 1
        # write back persistables + fetches
        for n in _persistable_names(program):
            if n in env:
                scope.set(n, env[n])
        for n in fetch_names:
            if n in env:
                scope.set(n, env[n])

    # -- compiled whole-block path ------------------------------------------
    def _run_compiled(self, program: Program, scope: Scope, feed,
                      fetch_names, return_numpy):
        block = program.global_block()
        feed_vals = {n: self._coerce_feed(block, n, v)
                     for n, v in feed.items()}
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]
        # signature from metadata only — np.asarray here would force a
        # blocking device->host copy of every feed on every step
        feed_sig = self._feed_signature(feed_vals)
        key = (program.fingerprint(), feed_sig, tuple(fetch_names),
               tuple(state_names))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._compile(program, state_names, fetch_names)
            self._cache[key] = fn

        state = {n: scope.get(n) for n in state_names}
        seed = self._seed_for_step(program)
        fetches, new_state = fn(state, feed_vals, jnp.uint32(seed))
        self._step += 1
        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _make_step(self, program: Program, state_names, fetch_names):
        """(state, feed, seed) -> (fetches, state') over the whole block —
        the single traced step both the per-dispatch and scanned paths
        compile."""
        tracer = BlockTracer(program.global_block())

        def step(state, feed, seed):
            env = dict(state)
            env.update(feed)
            ctx = OpContext(seed=seed)
            tracer.run(env, ctx)
            new_state = {n: env[n] for n in state_names}
            fetches = tuple(env[n] for n in fetch_names)
            return fetches, new_state

        return step

    @staticmethod
    def _feed_signature(feed_vals):
        return tuple(sorted(
            (n, tuple(getattr(v, "shape", np.shape(v))),
             str(getattr(v, "dtype", None) or np.asarray(v).dtype))
            for n, v in feed_vals.items()))

    def _compile(self, program: Program, state_names, fetch_names):
        step = self._make_step(program, state_names, fetch_names)
        return jax.jit(step, donate_argnums=(0,))

    # -- multi-step dispatch (device-resident training loop) ----------------
    def run_steps(self, program, feed=None, fetch_list=None, scope=None,
                  return_numpy=True):
        """Run K consecutive training steps in ONE device dispatch.

        Every array in `feed` carries a leading steps axis K; the jitted
        computation `lax.scan`s the whole-block step over it, carrying the
        persistable state on device, and returns each fetch stacked to
        [K, ...].  One dispatch + one feed transfer amortize per-step host
        latency K-fold — the difference between wall throughput and device
        throughput when dispatch crosses a high-latency link (measured
        r5 on the axon TPU tunnel: ~300 ms/step of dispatch overhead vs
        155 ms/step of device compute at BERT-base batch 32).

        TPU-first redesign of the reference's in-runtime trainer loops
        (train_from_dataset / multi-batch C++ trainer,
        paddle/fluid/framework/trainer.h:1): instead of a host loop calling
        the device once per batch, the loop itself is compiled onto the
        device.
        """
        from ..core.program import default_main_program
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [v.name if hasattr(v, "name") else str(v)
                       for v in (fetch_list or [])]
        block = program.global_block()
        feed_vals = {n: self._coerce_feed(block, n, v)
                     for n, v in feed.items()}
        if not feed_vals:
            raise ValueError("run_steps needs at least one stacked feed "
                             "to define the number of steps")
        k = None
        for n, v in feed_vals.items():
            shape = getattr(v, "shape", ())
            if len(shape) == 0:
                raise ValueError(
                    f"run_steps feed {n!r} is a scalar; every feed needs "
                    f"a leading steps axis (stack K per-step values)")
            k = shape[0] if k is None else k
            if shape[0] != k:
                raise ValueError(
                    f"feed {n!r} leading (steps) dim {shape[0]} != {k}")
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]
        key = ("run_steps", program.fingerprint(),
               self._feed_signature(feed_vals), tuple(fetch_names),
               tuple(state_names))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._compile_steps(program, state_names, fetch_names)
            self._cache[key] = fn

        # same side contracts as run(): elastic auto-checkpoint hook,
        # run counters, profiler span, FLAGS_check_nan_inf post-scan
        from ..incubate.checkpoint.auto_checkpoint import _auto_checkpoint
        _auto_checkpoint(self, program)
        from ..core.flags import flag
        from ..core.monitor import stat_add
        from ..profiler import RecordEvent
        stat_add("executor_run_times")
        state = {n: scope.get(n) for n in state_names}
        seeds = jnp.asarray(
            [self._seed_for_step(program) + i for i in range(k)],
            jnp.uint32)
        self._step += k
        with RecordEvent("Executor::RunSteps"):
            fetches, new_state = fn(state, feed_vals, seeds)
        for n, v in new_state.items():
            scope.set(n, v)
        results = [np.asarray(f) for f in fetches] if return_numpy \
            else list(fetches)
        if flag("check_nan_inf", False):
            self._check_nan_inf(fetch_names, results, scope)
        return results

    def _compile_steps(self, program: Program, state_names, fetch_names):
        step = self._make_step(program, state_names, fetch_names)

        def body(state, xs):
            feed, seed = xs
            fetches, new_state = step(state, feed, seed)
            return new_state, fetches

        def multi(state, feeds, seeds):
            new_state, fetches = jax.lax.scan(body, state, (feeds, seeds))
            return fetches, new_state

        return jax.jit(multi, donate_argnums=(0,))

    # -- helpers ------------------------------------------------------------
    def _coerce_feed(self, block, name, val):
        arr = jnp.asarray(val)
        try:
            var = block.var(name)
        except KeyError:
            return arr
        if var.dtype is not None and str(arr.dtype) != var.dtype:
            arr = arr.astype(np_dtype(var.dtype))
        return arr

    def _seed_for_step(self, program: Program) -> int:
        return (int(program.random_seed) * 1000003 + self._step) % (2 ** 31)

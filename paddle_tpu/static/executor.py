"""Executor: runs a Program by tracing its whole block into ONE jitted XLA
computation.

Analog of the reference executor stack
(/root/reference/python/paddle/fluid/executor.py:474 Executor,
 /root/reference/paddle/fluid/framework/executor.cc:474-480 per-op hot loop) —
but where the reference interprets op-by-op with per-kernel launches, here the
op list is composed into a single function (state, feed, seed) ->
(fetches, state') and `jax.jit`-ed with state buffers donated, so XLA fuses
the entire step (SURVEY.md §3.1 "the whole :474-480 loop becomes ONE traced
XLA computation").  Garbage collection (executor.cc:445-472 GC selection)
disappears: XLA buffer liveness subsumes it.

Startup programs are interpreted eagerly op-by-op — they run once, tracing
would only add compile latency.  Set FLAGS_eager_run=1 to interpret main
programs too (debug path, analog of the reference's sequential executor).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.program import Program, Block, default_main_program, OpRole
from ..core.place import CPUPlace, XLAPlace, Place, _current_expected_place
from ..core.dtype import np_dtype
from ..core import compile_cache as _ccache
from ..ops.registry import get_op_info, OpContext
from ..testing import chaos as _chaos

__all__ = ["Executor", "Scope", "global_scope", "scope_guard",
           "as_numpy", "BlockTracer"]


class Scope:
    """name -> device array store (analog of framework/scope.h:52, flattened:
    no parent chain — programs here use unique names)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def find_var(self, name: str):
        return _VarView(self, name) if name in self.vars else None

    def var(self, name: str):
        self.vars.setdefault(name, None)
        return _VarView(self, name)

    def set(self, name: str, value):
        self.vars[name] = value

    def get(self, name: str):
        return self.vars.get(name)

    def drop_kids(self):
        pass

    def keys(self):
        return self.vars.keys()


class _VarView:
    def __init__(self, scope, name):
        self._scope, self._name = scope, name

    def get_tensor(self):
        return self._scope.vars[self._name]

    def set(self, value, place=None):
        self._scope.vars[self._name] = jnp.asarray(value)


_global_scope = Scope()
_scope_stack = threading.local()


def global_scope() -> Scope:
    stack = getattr(_scope_stack, "stack", None)
    return stack[-1] if stack else _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        if not hasattr(_scope_stack, "stack"):
            _scope_stack.stack = []
        _scope_stack.stack.append(self.scope)
        return self.scope

    def __exit__(self, *a):
        _scope_stack.stack.pop()


def as_numpy(x):
    if isinstance(x, (list, tuple)):
        return [as_numpy(i) for i in x]
    return np.asarray(x)


# ---------------------------------------------------------------------------
# block tracing
# ---------------------------------------------------------------------------
class BlockTracer:
    """Composes a block's ops into one pure function over an environment of
    jax values.  Shared by Executor (jit path), the startup interpreter, and
    the distributed CompiledProgram (which traces under shard_map)."""

    def __init__(self, block: Block, skip_types=("feed", "fetch")):
        self.block = block
        self.skip_types = set(skip_types)

    def run(self, env: Dict[str, Any], ctx: OpContext,
            ops=None, on_op=None) -> Dict[str, Any]:
        for op in (ops if ops is not None else self.block.ops):
            if op.type in self.skip_types:
                continue
            self.run_op(op, env, ctx)
            if on_op is not None:
                on_op(op, env)
        return env

    def run_op(self, op, env: Dict[str, Any], ctx: OpContext):
        # sub-block ops (while/cond/static_rnn/...) reach their Program
        # through the context and recurse with their own BlockTracer
        ctx.program = self.block.program
        info = get_op_info(op.type)
        if info is None:
            raise NotImplementedError(
                f"op {op.type!r} has no registered kernel")
        ins: Dict[str, Any] = {}
        for slot in info.inputs:
            names = op.inputs.get(slot.name, [])
            if slot.duplicable:
                if slot.name.endswith("@GRAD"):
                    # cotangent lists must stay POSITION-ALIGNED with the
                    # forward output slot — an absent grad ('' name, e.g.
                    # a while's non-differentiable carried cond) is None,
                    # not dropped, or every grad after it lands on the
                    # wrong output
                    ins[slot.name] = [env.get(n) if n else None
                                      for n in names]
                else:
                    ins[slot.name] = [env[n] for n in names
                                      if n and n in env]
            else:
                n = names[0] if names else None
                ins[slot.name] = env.get(n) if n else None
        attrs = dict(op.attrs)
        outs = info.kernel(ins, attrs, ctx)
        for slot in info.outputs:
            names = op.outputs.get(slot.name, [])
            if not names:
                continue
            val = outs.get(slot.name) if outs else None
            if val is None:
                continue
            if slot.duplicable:
                for n, v in zip(names, val):
                    if n and v is not None:
                        env[n] = v
            else:
                if names[0]:
                    env[names[0]] = val
        return env


def _persistable_names(program: Program) -> List[str]:
    return sorted(v.name for b in program.blocks for v in b.vars.values()
                  if v.persistable)


def _unwrap_program(program):
    """Peel executable wrappers down to the underlying Program:
    ParallelExecutor wraps a CompiledProgram (``._compiled``) which wraps
    the Program (``._program``) — the checkpoint hook must reach the real
    Program through either."""
    for _ in range(4):
        if program is None or isinstance(program, Program):
            break
        inner = getattr(program, "_program", None)
        if inner is None:
            inner = getattr(program, "_compiled", None)
        if inner is None:
            break
        program = inner
    return program


def _wrapper_chips(program) -> int:
    """Device count of an executable wrapper's (already-built) mesh —
    the MFU denominator must scale with the chips that shared the step.
    Falls back to 1 when no mesh is discoverable."""
    for obj in (program, getattr(program, "_compiled", None)):
        mesh = getattr(obj, "_mesh", None) if obj is not None else None
        if mesh is not None:
            try:
                return max(1, int(len(mesh.devices.flat)))
            except Exception:
                pass
    return 1


_OPTIMIZER_OP_TYPES = frozenset(
    ("sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop", "lamb",
     "lars_momentum", "dgc_momentum", "ftrl", "adamax", "adadelta"))


def _is_training(program: Program) -> bool:
    """A program that updates state: has backward or optimizer ops.
    Distinguishes the real train program from startup (pure initializers)
    and eval programs when the checkpoint hook has to bind by itself."""
    return any(op.type.endswith("_grad") or op.type in _OPTIMIZER_OP_TYPES
               for b in program.blocks for op in b.ops)


class _CkptHook:
    """Periodic-checkpoint registration (enable_checkpointing).

    `program` may start as None and is latched by _maybe_checkpoint onto
    the first training program run afterwards; `run_scope` tracks the
    scope that program last ran in (for the preemption provider when no
    scope was given at enable time); `last` is the executor step of the
    most recent save (re-anchored by restore)."""

    __slots__ = ("manager", "program", "every", "scope", "last",
                 "run_scope")

    def __init__(self, manager, program, every, scope, last):
        self.manager = manager
        self.program = program
        self.every = every
        self.scope = scope
        self.last = last
        self.run_scope = None


class Executor:
    """exe = Executor(XLAPlace(0)); exe.run(startup); exe.run(main, feed,
    fetch_list) — the reference's two-program contract (executor.py:474)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or _current_expected_place()
        # persistent on-disk XLA cache (PADDLE_TPU_CACHE_DIR): a process
        # restart re-loads serialized executables instead of re-compiling
        _ccache.initialize()
        # compiled step cache: key -> (jitted fn, state names)
        self._cache: Dict[Tuple, Any] = {}
        # miss-key -> (bucket key, padded batch) memo so a recurring ragged
        # batch pays the bucket search once, not every step
        self._bucket_map: Dict[Tuple, Tuple] = {}
        # feed bucketing policy: "existing" pads a cache-missing ragged
        # batch up to the smallest already-compiled batch (training: the
        # epoch's last partial batch reuses the steady-state executable);
        # "pow2" additionally cold-compiles at the next power-of-two
        # bucket (variable-length inference: total traces bounded at
        # log2(max batch)); "off" disables padding.
        from ..core.flags import flag
        self.bucket_policy = flag("feed_bucketing", "existing")
        self._stats = {"hits": 0, "misses": 0, "traces": 0,
                       "bucket_hits": 0}
        self._step = 0
        # chaos fault-injection step index (testing/chaos.py): counts
        # TRAINING run()/run_steps() calls only (_chaos_step gates on
        # _is_training, so startup/eval runs never shift the spec) —
        # kill@<n> means "after the n-th train step"
        self._train_runs = 0
        # elastic micro-step count (distributed/elastic.py): unlike
        # _step, this counts ONLY elastic CompiledProgram runs (startup/
        # eval runs pollute _step), so global step = _elastic_steps // K
        # is exact and survives topology-shifted restores
        self._elastic_steps = 0
        # periodic checkpointing (enable_checkpointing): (manager,
        # program, every_n_steps, scope, last-saved-step)
        self._ckpt = None
        self._ckpt_barrier = None
        self._active_prefetcher = None
        self.last_restored_extra = None  # sidecar of the last resume
        # telemetry (docs/observability.md): chip peak FLOPs/s resolved
        # once per executor (None = not yet; 0.0 = unknown -> no MFU)
        self._peak_flops = None
        self._observed_steps = 0

    # -- public API ---------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True, fetch_var_name="fetch",
            feed_var_name="feed", use_prune=False):
        from ..distributed.compiled_program import CompiledProgram
        if isinstance(program, CompiledProgram) or (
                program is not None and not isinstance(program, Program)
                and hasattr(program, "_run")):
            # CompiledProgram / Pipeline / PS trainer program dispatch.
            # The checkpoint hook still fires: multi-chip pretraining is
            # the workload the checkpoint tier exists for.
            import time as _time
            _t0 = _time.perf_counter()
            results = program._run(self, feed, fetch_list, scope,
                                   return_numpy)
            self._observe_step(program, _time.perf_counter() - _t0,
                               feed or {}, chips=_wrapper_chips(program))
            # resolve the scope the wrapper actually ran in: some wrappers
            # (ParallelExecutor) carry their own _scope — snapshotting
            # global_scope() instead would commit an EMPTY checkpoint
            self._maybe_checkpoint(
                program, scope or getattr(program, "_scope", None)
                or global_scope())
            self._chaos_step(program)
            return results
        if getattr(program, "_ps_server_config", None):
            # pserver program: exe.run(pserver_prog) == listen_and_serv
            from ..distributed.ps.kv_server import KVServer
            cfg = program._ps_server_config
            server = KVServer(cfg["endpoint"],
                              num_trainers=cfg.get("num_trainers", 1))
            server.serve()  # blocks until a SHUTDOWN rpc
            return []
        program = program if program is not None else default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in (fetch_list or [])]

        if self._program_is_startup(program):
            self._run_eager(program, scope, feed, fetch_names)
            return [] if not fetch_names else [
                as_numpy(scope.get(n)) if return_numpy else scope.get(n)
                for n in fetch_names]

        # elastic auto-checkpoint hook (reference executor.py:1194)
        from ..incubate.checkpoint.auto_checkpoint import _auto_checkpoint
        _auto_checkpoint(self, program)

        from ..core.flags import flag
        from ..core.monitor import stat_add
        from ..profiler import RecordEvent
        import time as _time
        stat_add("executor_run_times")
        _t0 = _time.perf_counter()
        with RecordEvent("Executor::Run"):
            if flag("eager_run", False):
                self._run_eager(program, scope, feed, fetch_names)
                fetched = [scope.get(n) for n in fetch_names]
                results = [as_numpy(f) for f in fetched] \
                    if return_numpy else fetched
            else:
                results = self._run_compiled(program, scope, feed,
                                             fetch_names, return_numpy)
        self._observe_step(program, _time.perf_counter() - _t0, feed)
        if flag("check_nan_inf", False):
            self._check_nan_inf(fetch_names, results, scope,
                                program=program)
        self._maybe_checkpoint(program, scope)
        self._chaos_step(program)
        return results

    def _chaos_step(self, program):
        """Count this run toward the chaos step index ONLY when it was a
        TRAINING run: the PADDLE_TPU_CHAOS contract is 'after the n-th
        train step', and an interleaved eval/test-program run must not
        shift the injected-fault positions.  Training-ness is cached on
        the (unwrapped) program; everything here is skipped when chaos
        is off."""
        if not _chaos.enabled():
            return
        p = _unwrap_program(program)
        cached = getattr(p, "_chaos_is_training", None)
        if cached is None:
            cached = isinstance(p, Program) and _is_training(p)
            try:
                p._chaos_is_training = cached
            except (AttributeError, TypeError):  # exotic wrapper
                pass
        if cached:
            self._train_runs += 1
            _chaos.step_hook(self._train_runs)

    # -- step telemetry (docs/observability.md) -----------------------------
    @staticmethod
    def _is_training_cached(p) -> bool:
        cached = getattr(p, "_telemetry_is_training", None)
        if cached is None:
            cached = isinstance(p, Program) and _is_training(p)
            try:
                p._telemetry_is_training = cached
            except (AttributeError, TypeError):
                pass
        return cached

    @staticmethod
    def _feed_tokens(feed_vals, stacked: bool) -> int:
        """Tokens processed by one dispatch, inferred from the feed: the
        largest >=2-D integer feed's numel (ids-style models — the
        labels feed ties, max() is stable); else batch rows (x-style
        models).  `stacked` marks run_steps feeds ([K, B, ...]: rows
        are the two leading dims)."""
        best_int = 0
        rows = 0
        for v in feed_vals.values():
            shape = tuple(getattr(v, "shape", ()) or ())
            if not shape:
                continue
            dt = getattr(v, "dtype", None)
            try:
                kind = np.dtype(str(dt)).kind if dt is not None else "?"
            except TypeError:  # framework dtype numpy can't parse
                kind = "?"
            if kind in ("i", "u") and len(shape) >= 2:
                n = 1
                for d in shape:
                    n *= int(d)
                best_int = max(best_int, n)
            lead = int(shape[0])
            if stacked and len(shape) >= 2:
                lead *= int(shape[1])
            rows = max(rows, lead)
        return best_int or rows

    @staticmethod
    def _feed_batch(feed_vals, stacked: bool) -> int:
        """Per-step batch from the feed's leading dims (the -1 binding
        for the cached FLOPs/HBM walks); `stacked` = run_steps feeds
        whose per-step batch is axis 1.  The MOST COMMON candidate wins
        (ties -> largest) so a lone non-batch feed — a fed lr of shape
        [1], a lookup table — cannot poison the per-program cache."""
        counts: Dict[int, int] = {}
        for v in feed_vals.values():
            shape = tuple(getattr(v, "shape", ()) or ())
            if len(shape) >= (2 if stacked else 1):
                b = int(shape[1] if stacked else shape[0])
                counts[b] = counts.get(b, 0) + 1
        if not counts:
            return 0
        return max(counts, key=lambda b: (counts[b], b))

    def _flops_per_step(self, p, batch) -> Optional[int]:
        """analyze_flops total for this program at `batch`, cached on
        the program (one IR walk per distinct batch, then a dict hit)."""
        try:
            cache = p.__dict__.setdefault("_flops_by_batch", {})
        except (AttributeError, TypeError):
            return None
        if batch not in cache:
            try:
                from .flops_analysis import analyze_flops
                cache[batch] = analyze_flops(p, batch=batch)[
                    "total_flops"]
            except Exception:
                cache[batch] = None  # telemetry never kills training
        return cache[batch]

    def _observe_step(self, program, dt, feed_vals, steps=1, chips=1,
                      stacked=None):
        """Per-train-step telemetry: wall time, tokens/s, achieved-vs-
        peak MFU, retrace count into core/monitor; one journal event;
        one heartbeat.  Costs a handful of registry writes when nothing
        is armed; skipped entirely for non-training programs (startup /
        eval).  Fully fenced: telemetry must never kill a training run,
        so ANY failure here (unparseable feed dtype, a user-registered
        metric-name collision, a sick disk under the journal) degrades
        to a silently skipped observation."""
        p = _unwrap_program(program)
        if not self._is_training_cached(p):
            return
        try:
            self._observe_step_inner(
                p, dt, feed_vals, steps, chips,
                steps > 1 if stacked is None else stacked)
        except Exception:
            pass

    def _observe_step_inner(self, p, dt, feed_vals, steps, chips,
                            stacked):
        from ..core.monitor import gauge_set, hist_observe, stat_add
        from ..observability import heartbeat as _hb
        from ..observability import journal as _journal
        from ..observability.sidecar import maybe_start_from_env
        maybe_start_from_env()
        self._observed_steps += steps
        stat_add("train.steps", steps)
        step_ms = dt * 1e3 / max(1, steps)
        hist_observe("train.step_ms", step_ms)
        gauge_set("executor.retraces", self._stats["traces"])
        tokens = self._feed_tokens(feed_vals, stacked=stacked)
        tps = None
        if tokens and dt > 0:
            tps = tokens / dt
            gauge_set("train.tokens_per_sec", tps)
        mfu = None
        if self._peak_flops is None:
            from .flops_analysis import peak_flops_per_chip
            try:
                self._peak_flops = float(peak_flops_per_chip())
            except Exception:
                self._peak_flops = 0.0
        if self._peak_flops and dt > 0:
            batch = self._feed_batch(feed_vals, stacked=stacked)
            flops = self._flops_per_step(p, batch) if batch else None
            if flops:
                mfu = (flops * steps) / dt / (self._peak_flops
                                              * max(1, chips))
                gauge_set("train.mfu", mfu)
        # predicted-vs-ground-truth HBM: the estimate once per program,
        # the allocator's answer every 64 steps (a C call, not free)
        if self._observed_steps == steps or \
                self._observed_steps % 64 < steps:
            self._observe_hbm(p, feed_vals, stacked)
        _hb.maybe_beat(self._step, wall_ms=round(step_ms, 3))
        if _journal.journal_enabled():
            ev = {"step": self._step, "wall_ms": round(step_ms, 3)}
            if steps > 1:
                ev["micro_steps"] = steps
            if tps is not None:
                ev["tokens_per_sec"] = round(tps, 1)
            if mfu is not None:
                ev["mfu"] = round(mfu, 5)
            _journal.emit("step", **ev)

    def _observe_hbm(self, p, feed_vals, stacked):
        from ..core.monitor import gauge_set
        try:
            batch = self._feed_batch(feed_vals, stacked=stacked)
            cache = p.__dict__.setdefault("_hbm_by_batch", {})
            if batch and batch not in cache:
                from .memory_analysis import analyze_program
                cache[batch] = analyze_program(p, batch=batch)[
                    "peak_bytes"]
            if batch and cache.get(batch):
                gauge_set("hbm.predicted_peak_bytes", cache[batch])
            import jax as _jax
            stats = _jax.local_devices()[0].memory_stats() or {}
            peak = stats.get("peak_bytes_in_use")
            if peak:
                gauge_set("hbm.device_peak_bytes", int(peak))
        except Exception:
            pass  # backends without memory_stats / exotic programs

    def _check_nan_inf(self, fetch_names, results, scope, program=None,
                       steps=1):
        """FLAGS_check_nan_inf (reference details/nan_inf_utils_detail —
        per-op output scan; here: fetches + persistable state after the
        jitted step, which bounds the same failure).

        Each finding names the PRODUCING op (type, op_uid, op index) and
        the value's dtype, resolved from `program`'s IR — not just the
        fetch name — so a NaN points at the kernel that minted it, like
        the reference's CheckOpHasNanOrInf.  Under ``run_steps`` (where
        fetches are stacked ``[K, ...]``) the report also names the
        first micro-step whose slice went non-finite.  Works identically
        for run() and run_steps(); the eager path has the sharper
        `_per_op_nan_scan`.  (docs/static_analysis.md "NaN/Inf
        debugging".)"""
        bad = []  # (kind, name, array, step_idx or None)
        for n, v in zip(fetch_names, results or []):
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                step_idx = None
                if steps > 1 and arr.ndim >= 1 and arr.shape[0] == steps:
                    per_step = np.isfinite(
                        arr.reshape(steps, -1)).all(axis=1)
                    step_idx = int(np.argmin(per_step))
                bad.append(("fetch", n, arr, step_idx))
        scan_names = _persistable_names(program) if program is not None \
            else list(scope.keys())
        for n in scan_names:
            v = scope.get(n)
            if v is None:
                continue
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                bad.append(("var", n, arr, None))
        if not bad:
            return
        producers = {}
        if program is not None:
            for b in program.blocks:
                for i, op in enumerate(b.ops):
                    for out_name in op.output_names():
                        if out_name:
                            # keep the LAST writer: that is the value the
                            # step actually committed
                            producers[out_name] = (b.idx, i, op)
        msgs = []
        for kind, n, arr, step_idx in bad:
            msg = f"{kind} {n!r} (dtype {arr.dtype})"
            if step_idx is not None:
                msg += f", first non-finite at micro-step {step_idx}"
            hit = producers.get(n)
            if hit is not None:
                bi, oi, op = hit
                msg += (f", produced by op {op.type!r} "
                        f"(uid {op.attrs.get('op_uid')}, "
                        f"block {bi} op {oi})")
            msgs.append(msg)
        raise RuntimeError(
            "FLAGS_check_nan_inf: non-finite values in "
            + "; ".join(msgs))

    # -- dataset-driven training (MultiTrainer path, executor.py:1345) ------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        from ..distributed.dataset import run_from_dataset
        from ..core.program import default_main_program
        if fetch_handler is not None:
            raise NotImplementedError(
                "fetch_handler callbacks are not supported; poll "
                "fetch_list/print_period instead")
        program = program if program is not None else default_main_program()
        if thread:
            dataset.set_thread(thread)
        return run_from_dataset(self, program, dataset, scope,
                                fetch_list, fetch_info, print_period, debug)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Like train_from_dataset but guaranteed side-effect-free on the
        parameters (reference executor.py:1476 contract): training-role
        ops (backward/optimizer/lr-sched) are stripped and test mode is
        applied before running."""
        from ..core.program import default_main_program, OpRole
        program = program if program is not None else default_main_program()
        infer = program.clone(for_test=True)
        blk = infer.global_block()
        train_roles = (OpRole.Backward, OpRole.Optimize, OpRole.LRSched,
                       OpRole.Optimize | OpRole.LRSched)
        blk.ops = [op for op in blk.ops
                   if op.attrs.get(OpRole.KEY, OpRole.Forward)
                   not in train_roles]
        return self.train_from_dataset(infer, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _per_op_nan_scan(op, env):
        """Eager-mode per-op output scan under FLAGS_check_nan_inf — names
        the op that produced the first non-finite value (reference
        details/nan_inf_utils_detail.cc CheckOpHasNanOrInf)."""
        for n in op.output_names():
            v = env.get(n)
            if v is None or not hasattr(v, "dtype"):
                continue
            if jnp.issubdtype(v.dtype, jnp.floating) and \
                    not bool(jnp.isfinite(v).all()):
                raise RuntimeError(
                    f"FLAGS_check_nan_inf: op {op.type!r} produced "
                    f"non-finite values in output {n!r}")

    def close(self):
        """Release the in-process jitted-step cache.  Idempotent — safe to
        call repeatedly (reference executor.py:658 close contract).  The
        persistent on-disk cache (PADDLE_TPU_CACHE_DIR) is deliberately
        untouched: it is process-shared state, and the whole point is that
        the NEXT process starts hot.  Counters survive close so post-hoc
        `cache_stats()` still reports the session."""
        self._cache.clear()
        self._bucket_map.clear()

    # -- eager interpreter (startup / debug) --------------------------------
    def _program_is_startup(self, program: Program) -> bool:
        """Explicit two-program contract: program_guard / the default-program
        registry stamp `_role` ("startup" runs eagerly once, "main" takes the
        jit+donate path).  Unmarked programs (hand-built, deserialized) fall
        back to the init-op heuristic."""
        if program._role is not None:
            return program._role == "startup"
        b = program.global_block()
        init_types = {"fill_constant", "uniform_random", "gaussian_random",
                      "truncated_gaussian_random", "assign_value", "eye",
                      "c_broadcast", "broadcast", "seed", "range", "linspace"}
        return len(b.ops) > 0 and all(op.type in init_types for op in b.ops)

    def _run_eager(self, program: Program, scope: Scope, feed, fetch_names):
        from ..core.flags import flag
        block = program.global_block()
        env = {k: v for k, v in scope.vars.items() if v is not None}
        for name, val in feed.items():
            env[name] = self._coerce_feed(block, name, val)
        ctx = OpContext(seed=self._seed_for_step(program))
        on_op = self._per_op_nan_scan if flag("check_nan_inf", False) else None
        BlockTracer(block).run(env, ctx, on_op=on_op)
        self._step += 1
        # write back persistables + fetches
        for n in _persistable_names(program):
            if n in env:
                scope.set(n, env[n])
        for n in fetch_names:
            if n in env:
                scope.set(n, env[n])

    # -- compiled whole-block path ------------------------------------------
    def _run_compiled(self, program: Program, scope: Scope, feed,
                      fetch_names, return_numpy):
        block = program.global_block()
        feed_vals = {n: self._coerce_feed(block, n, v)
                     for n, v in feed.items()}
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]
        # signature from metadata only — np.asarray here would force a
        # blocking device->host copy of every feed on every step
        feed_sig = self._feed_signature(feed_vals)
        key = (program.fingerprint(), feed_sig, tuple(fetch_names),
               tuple(state_names))
        fn = self._cache.get(key)
        bucket = None  # (real batch, padded batch)
        if fn is None:
            bucketed = self._bucket_lookup(key, feed_vals)
            if bucketed is not None:
                key, feed_vals, bucket = bucketed
                fn = self._cache.get(key)
        if fn is None:
            # env-gated IR verification on the first compile of each
            # program (PADDLE_TPU_VERIFY — static/verifier.py): the IR
            # walk rides the already-slow trace path only
            from .verifier import verify_first_compile
            verify_first_compile(program, fetch_list=fetch_names)
            self._record("miss")
            self._record("trace")
            from ..observability.journal import emit as _jemit
            _jemit("compile", mode="run", fingerprint=str(key[0])[:16])
            fn = self._compile(program, state_names, fetch_names)
            self._cache[key] = fn
        else:
            self._record("hit", bucketed=bucket is not None)

        state = {n: scope.get(n) for n in state_names}
        seed = self._seed_for_step(program)
        fetches, new_state = fn(state, feed_vals, jnp.uint32(seed))
        self._step += 1
        for n, v in new_state.items():
            scope.set(n, v)
        if bucket is not None:
            fetches = self._unpad_fetches(fetches, *bucket,
                                          block=block,
                                          fetch_names=fetch_names)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    # -- shape bucketing -----------------------------------------------------
    def _record(self, kind, bucketed=False):
        self._stats[kind + "es" if kind.endswith("s") else kind + "s"] += 1
        if kind == "hit":
            _ccache.record_hit(bucketed)
            if bucketed:
                self._stats["bucket_hits"] += 1
        elif kind == "miss":
            _ccache.record_miss()
        elif kind == "trace":
            _ccache.record_trace()

    @staticmethod
    def _common_leading_dim(feed_sig):
        """The shared batch dim of a feed signature, or None when feeds
        disagree / any feed is rank-0 (no well-defined batch axis)."""
        dims = set()
        for _, shape, _ in feed_sig:
            if not shape:
                return None
            dims.add(int(shape[0]))
        return dims.pop() if len(dims) == 1 else None

    def _bucket_lookup(self, miss_key, feed_vals):
        """On a step-cache miss, try to serve the step from a LARGER
        already-compiled batch bucket instead of tracing a fresh shape.

        Returns (bucket_key, padded_feed_vals, original_batch) or None.
        Policy "existing": pad up to the smallest compiled batch >= b with
        identical trailing dims/dtypes (epoch-tail ragged batch -> the
        steady-state executable).  Policy "pow2": when nothing compiled
        fits, target the next power-of-two >= b so variable-length
        inference settles into at most log2(max) buckets.  Padding
        repeats the final row — values stay in-domain (valid token ids,
        finite floats) and real rows' per-row fetches are bit-identical
        (row-independent programs); `_unpad_fetches` slices fetches back.
        Batch-reduced fetches (mean loss) and state updates DO see the
        duplicated rows — same tradeoff as pad-vs-drop-last in any
        static-shape pipeline (docs/perf.md)."""
        policy = self.bucket_policy
        if policy not in ("existing", "pow2") or not feed_vals:
            return None
        memo = self._bucket_map.get(miss_key)
        if memo is not None:
            bucket_key, target = memo
            return (bucket_key, self._pad_feeds(feed_vals, target), target)
        fp, feed_sig, fetch_names, state_names = miss_key
        b = self._common_leading_dim(feed_sig)
        if b is None:
            return None

        def rebucket(sig, new_b):
            return tuple((n, (new_b,) + tuple(s[1:]), dt)
                         for n, s, dt in sig)

        candidates = []
        for k in self._cache:
            if len(k) != 4 or k[0] != fp or k[2] != fetch_names \
                    or k[3] != state_names:
                continue
            cand_b = self._common_leading_dim(k[1])
            if cand_b is None or cand_b < b:
                continue
            if k[1] == rebucket(feed_sig, cand_b):
                candidates.append(cand_b)
        if policy == "pow2":
            # the pow2 bucket competes with existing entries: serving a
            # batch-5 stream must not ride a previously-compiled batch-64
            # executable forever (12.8x the compute) just because 64 was
            # seen first — one cheap 8-bucket compile amortizes at once
            candidates.append(1 << (b - 1).bit_length())
        if not candidates:
            return None
        target_b = min(candidates)
        if target_b == b:
            return None  # already a bucket boundary: compile exact
        bucket_key = (fp, rebucket(feed_sig, target_b), fetch_names,
                      state_names)
        self._bucket_map[miss_key] = (bucket_key, (b, target_b))
        return (bucket_key, self._pad_feeds(feed_vals, (b, target_b)),
                (b, target_b))

    @staticmethod
    def _pad_feeds(feed_vals, target):
        b, target_b = target
        out = {}
        for n, v in feed_vals.items():
            pad = jnp.repeat(v[-1:], target_b - b, axis=0)
            out[n] = jnp.concatenate([v, pad], axis=0)
        return out

    @classmethod
    def _unpad_fetches(cls, fetches, orig_batch, padded_batch, block=None,
                       fetch_names=()):
        """Mask-aware fetch un-padding: slice per-row fetches back to the
        real batch.  A fetch whose runtime leading dim equals the padded
        bucket is sliced unless the program says its dim 0 is NOT the
        batch (`_fetch_batch_dim_dynamic`): persistable vars (weights)
        never slice; a declared STATIC dim 0 exactly equal to the bucket
        marks a coincidence (a [64, k] temp while serving the 64-bucket)
        and passes through.  Declared dynamic (-1/None) dims, stale
        concrete dims (traced programs record the example batch), and
        undeclared temps all slice."""
        names = list(fetch_names) + [None] * (len(fetches) -
                                              len(fetch_names))
        return tuple(
            f[:orig_batch]
            if getattr(f, "ndim", 0) >= 1 and f.shape[0] == padded_batch
            and cls._fetch_batch_dim_dynamic(block, n, padded_batch)
            else f
            for f, n in zip(fetches, names))

    def memory_report(self, program=None, feed=None, scope=None,
                      batch=None, dp_shard=None):
        """Compile-time HBM accounting for one training step of
        `program` (static/memory_analysis.py): the op-IR liveness
        estimate always; XLA ground truth via
        ``jit(step).lower(...).compile().memory_analysis()`` when `feed`
        is given and the installed backend supports it.

        Returns ``{"estimate": <analyze_program dict>, "peak_bytes",
        "budget_bytes", "fits", "xla": {...} | None}``.  `batch` binds
        symbolic -1 dims for the estimate; when omitted it is inferred
        from the feed's leading dim.  The estimate needs NO device —
        fits-or-OOMs for a TPU config is answered on any host."""
        from ..core.program import default_main_program
        from .memory_analysis import analyze_program
        program = _unwrap_program(program or default_main_program())
        if batch is None and feed:
            for v in feed.values():
                shape = getattr(v, "shape", None) or np.shape(v)
                if len(shape):
                    batch = int(shape[0])
                    break
        est = analyze_program(program, batch=batch, dp_shard=dp_shard)
        report = {"estimate": est, "peak_bytes": est["peak_bytes"],
                  "budget_bytes": est["budget_bytes"],
                  "fits": est["fits"], "xla": None}
        if feed:
            scope = scope or global_scope()
            block = program.global_block()
            feed_vals = {n: self._coerce_feed(block, n, v)
                         for n, v in feed.items()}
            state_names = [n for n in _persistable_names(program)
                           if scope.get(n) is not None]
            state = {n: scope.get(n) for n in state_names}
            try:
                step = self._make_step(program, state_names, [])
                lowered = jax.jit(step, donate_argnums=(0,)).lower(
                    state, feed_vals, jnp.uint32(0))
                ma = lowered.compile().memory_analysis()
                xla = {}
                for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                          "output_size_in_bytes", "alias_size_in_bytes",
                          "generated_code_size_in_bytes"):
                    v = getattr(ma, k, None)
                    if v is not None:
                        xla[k] = int(v)
                if xla:
                    xla["peak_bytes"] = (
                        xla.get("argument_size_in_bytes", 0)
                        + xla.get("temp_size_in_bytes", 0)
                        + xla.get("output_size_in_bytes", 0)
                        - xla.get("alias_size_in_bytes", 0))
                    report["xla"] = xla
            except Exception as e:  # backend without memory_analysis()
                report["xla_error"] = repr(e)
        return report

    def cache_stats(self) -> Dict[str, int]:
        """Hot-path cache accounting for THIS executor: ``hits`` /
        ``misses`` / ``traces`` (whole-block jit retraces — the number
        that must stop growing after warmup) / ``bucket_hits`` (hits that
        needed batch padding), plus the process-wide persistent-cache
        location and entry count from core/compile_cache.py."""
        out = dict(self._stats)
        out["persistent_dir"] = _ccache.cache_dir()
        out["persistent_entries"] = _ccache.persistent_entries()
        return out

    def _make_step(self, program: Program, state_names, fetch_names):
        """(state, feed, seed) -> (fetches, state') over the whole block —
        the single traced step both the per-dispatch and scanned paths
        compile."""
        tracer = BlockTracer(program.global_block())

        def step(state, feed, seed):
            env = dict(state)
            env.update(feed)
            ctx = OpContext(seed=seed)
            tracer.run(env, ctx)
            new_state = {n: env[n] for n in state_names}
            fetches = tuple(env[n] for n in fetch_names)
            return fetches, new_state

        return step

    @staticmethod
    def _feed_signature(feed_vals):
        return tuple(sorted(
            (n, tuple(getattr(v, "shape", np.shape(v))),
             str(getattr(v, "dtype", None) or np.asarray(v).dtype))
            for n, v in feed_vals.items()))

    def _compile(self, program: Program, state_names, fetch_names):
        step = self._make_step(program, state_names, fetch_names)
        return jax.jit(step, donate_argnums=(0,))

    # -- multi-step dispatch (device-resident training loop) ----------------
    def run_steps(self, program, feed=None, fetch_list=None, scope=None,
                  return_numpy=True):
        """Run K consecutive training steps in ONE device dispatch.

        Every array in `feed` carries a leading steps axis K; the jitted
        computation `lax.scan`s the whole-block step over it, carrying the
        persistable state on device, and returns each fetch stacked to
        [K, ...].  One dispatch + one feed transfer amortize per-step host
        latency K-fold — the difference between wall throughput and device
        throughput when dispatch crosses a high-latency link (measured
        r5 on the axon TPU tunnel: ~300 ms/step of dispatch overhead vs
        155 ms/step of device compute at BERT-base batch 32).

        TPU-first redesign of the reference's in-runtime trainer loops
        (train_from_dataset / multi-batch C++ trainer,
        paddle/fluid/framework/trainer.h:1): instead of a host loop calling
        the device once per batch, the loop itself is compiled onto the
        device.

        Stacked feeds ride the same FLAGS_feed_bucketing policy as
        run(): a ragged PER-STEP batch pads up to an already-compiled
        stacked bucket (axis 1; fetches are sliced back), and a short
        final chunk (K' < the compiled steady K) is served step-by-step
        through run() instead of retracing the whole scan — the steps
        axis is never padded, because scanned padding steps would replay
        extra optimizer updates.
        """
        from ..core.program import default_main_program
        from ..distributed.compiled_program import CompiledProgram
        program = program or default_main_program()
        if isinstance(program, CompiledProgram) or (
                not isinstance(program, Program)
                and hasattr(program, "_run_steps")):
            # multi-chip scanned dispatch (incl. the elastic K-micro-step
            # window: one global step = ONE device call instead of K
            # host dispatches — distributed/elastic.py)
            import time as _time
            _t0 = _time.perf_counter()
            results = program._run_steps(self, feed, fetch_list, scope,
                                         return_numpy)
            k = 0
            for v in (feed or {}).values():
                k = int(getattr(v, "shape", (1,))[0] or 1)
                break
            self._observe_step(program, _time.perf_counter() - _t0,
                               feed or {}, steps=max(1, k),
                               chips=_wrapper_chips(program), stacked=True)
            self._maybe_checkpoint(
                program, scope or getattr(program, "_scope", None)
                or global_scope())
            self._chaos_step(program)
            return results
        scope = scope or global_scope()
        feed = feed or {}
        if getattr(program, "_elastic_meta", None) is not None:
            raise NotImplementedError(
                "run_steps on a RAW elastic Program is not supported: "
                "the schedule's K is resolved from the mesh at trace "
                "time, which only exists under CompiledProgram — wrap "
                "it (CompiledProgram(main).with_data_parallel(...)) and "
                "run_steps scans the K-micro-step window in one device "
                "dispatch (distributed/elastic.py)")
        fetch_names = [v.name if hasattr(v, "name") else str(v)
                       for v in (fetch_list or [])]
        block = program.global_block()
        feed_vals = {n: self._coerce_feed(block, n, v)
                     for n, v in feed.items()}
        if not feed_vals:
            raise ValueError("run_steps needs at least one stacked feed "
                             "to define the number of steps")
        k = None
        for n, v in feed_vals.items():
            shape = getattr(v, "shape", ())
            if len(shape) == 0:
                raise ValueError(
                    f"run_steps feed {n!r} is a scalar; every feed needs "
                    f"a leading steps axis (stack K per-step values)")
            k = shape[0] if k is None else k
            if shape[0] != k:
                raise ValueError(
                    f"feed {n!r} leading (steps) dim {shape[0]} != {k}")
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]
        key = ("run_steps", program.fingerprint(),
               self._feed_signature(feed_vals), tuple(fetch_names),
               tuple(state_names))
        fn = self._cache.get(key)
        bucket = None  # (real per-step batch, padded per-step batch)
        if fn is None:
            bucketed = self._bucket_lookup_steps(key, feed_vals)
            if bucketed is not None:
                key, feed_vals, bucket = bucketed
                fn = self._cache.get(key)
        if fn is None and self.bucket_policy != "off" and \
                self._has_longer_scan(key, k):
            # short FINAL chunk (K' < a compiled steady K): padding the
            # steps axis would replay extra optimizer updates, so the
            # chunk runs step-by-step through run() — which buckets the
            # batch dim itself — instead of retracing the whole scan.
            # State threading and per-step seeds are identical to the
            # scanned path (same _seed_for_step walk over self._step).
            return self._run_steps_fallback(program, feed_vals, k,
                                            fetch_list, scope,
                                            return_numpy)
        if fn is None:
            from .verifier import verify_first_compile
            verify_first_compile(program, fetch_list=fetch_names)
            self._record("miss")
            self._record("trace")
            from ..observability.journal import emit as _jemit
            _jemit("compile", mode="run_steps",
                   fingerprint=str(key[1])[:16])
            fn = self._compile_steps(program, state_names, fetch_names)
            self._cache[key] = fn
        else:
            self._record("hit", bucketed=bucket is not None)

        # same side contracts as run(): elastic auto-checkpoint hook,
        # run counters, profiler span, FLAGS_check_nan_inf post-scan
        from ..incubate.checkpoint.auto_checkpoint import _auto_checkpoint
        _auto_checkpoint(self, program)
        from ..core.flags import flag
        from ..core.monitor import stat_add
        from ..profiler import RecordEvent
        stat_add("executor_run_times")
        state = {n: scope.get(n) for n in state_names}
        seeds = jnp.asarray(
            [self._seed_for_step(program) + i for i in range(k)],
            jnp.uint32)
        self._step += k
        import time as _time
        _t0 = _time.perf_counter()
        with RecordEvent("Executor::RunSteps"):
            fetches, new_state = fn(state, feed_vals, seeds)
        _dt = _time.perf_counter() - _t0
        for n, v in new_state.items():
            scope.set(n, v)
        # stacked=True explicitly: a K=1 run_steps feed still has its
        # per-step batch on axis 1, not axis 0
        self._observe_step(program, _dt, feed_vals, steps=int(k),
                           stacked=True)
        if bucket is not None:
            fetches = self._unpad_steps_fetches(fetches, *bucket,
                                                block=block,
                                                fetch_names=fetch_names)
        results = [np.asarray(f) for f in fetches] if return_numpy \
            else list(fetches)
        if flag("check_nan_inf", False):
            self._check_nan_inf(fetch_names, results, scope,
                                program=program, steps=int(k))
        self._maybe_checkpoint(program, scope)
        self._chaos_step(program)
        return results

    def _compile_steps(self, program: Program, state_names, fetch_names):
        step = self._make_step(program, state_names, fetch_names)

        def body(state, xs):
            feed, seed = xs
            fetches, new_state = step(state, feed, seed)
            return new_state, fetches

        def multi(state, feeds, seeds):
            new_state, fetches = jax.lax.scan(body, state, (feeds, seeds))
            return fetches, new_state

        return jax.jit(multi, donate_argnums=(0,))

    # -- run_steps shape bucketing ------------------------------------------
    def _bucket_lookup_steps(self, miss_key, feed_vals):
        """run_steps analog of _bucket_lookup: on a scan-cache miss, pad
        the PER-STEP batch dim (axis 1 of every stacked feed) up to the
        smallest already-compiled stacked bucket with the SAME step
        count K.  The steps axis is never padded — extra scanned steps
        would replay extra optimizer updates.  Same duplicated-row
        caveats as run()'s bucketing (docs/perf.md)."""
        policy = self.bucket_policy
        if policy not in ("existing", "pow2") or not feed_vals:
            return None
        memo = self._bucket_map.get(miss_key)
        if memo is not None:
            bucket_key, target = memo
            return (bucket_key, self._pad_steps_feeds(feed_vals, target),
                    target)
        tag, fp, feed_sig, fetch_names, state_names = miss_key
        dims = set()
        for _, shape, _ in feed_sig:
            if len(shape) < 2:
                return None
            dims.add(int(shape[1]))
        if len(dims) != 1:
            return None
        b = dims.pop()

        def rebucket(sig, new_b):
            return tuple((n, (s[0], new_b) + tuple(s[2:]), dt)
                         for n, s, dt in sig)

        candidates = []
        for k in self._cache:
            if len(k) != 5 or k[0] != tag or k[1] != fp \
                    or k[3] != fetch_names or k[4] != state_names:
                continue
            cdims = {int(s[1]) for _, s, _ in k[2] if len(s) >= 2}
            if len(cdims) != 1:
                continue
            cand_b = cdims.pop()
            if cand_b < b:
                continue
            if k[2] == rebucket(feed_sig, cand_b):
                candidates.append(cand_b)
        if not candidates:
            return None
        target_b = min(candidates)
        if target_b == b:
            return None
        bucket_key = (tag, fp, rebucket(feed_sig, target_b), fetch_names,
                      state_names)
        self._bucket_map[miss_key] = (bucket_key, (b, target_b))
        return (bucket_key, self._pad_steps_feeds(feed_vals, (b, target_b)),
                (b, target_b))

    @staticmethod
    def _pad_steps_feeds(feed_vals, target):
        b, target_b = target
        out = {}
        for n, v in feed_vals.items():
            pad = jnp.repeat(v[:, -1:], target_b - b, axis=1)
            out[n] = jnp.concatenate([v, pad], axis=1)
        return out

    @staticmethod
    def _fetch_batch_dim_dynamic(block, name, padded_batch):
        """Shared declared-shape heuristic for fetch un-padding: does
        the program say this fetch's dim 0 is the (padded) batch?  Used
        by _unpad_fetches (run) and _unpad_steps_fetches (run_steps,
        where the per-step dim 0 is the stacked axis 1)."""
        if block is None:
            return True
        try:
            var = block.var(name)
        except (KeyError, TypeError):
            return True  # unnamed fetch / temp var without declared shape
        if getattr(var, "persistable", False):
            return False
        shape = getattr(var, "shape", None)
        if not shape or shape[0] in (-1, None):
            return True
        return shape[0] != padded_batch

    def _unpad_steps_fetches(self, fetches, orig_batch, padded_batch,
                             block=None, fetch_names=()):
        """Slice stacked fetches [K, padded_b, ...] back to the real
        per-step batch along axis 1 (the per-step dim 0)."""
        names = list(fetch_names) + [None] * (len(fetches) -
                                              len(fetch_names))
        out = []
        for f, n in zip(fetches, names):
            if getattr(f, "ndim", 0) >= 2 and f.shape[1] == padded_batch \
                    and self._fetch_batch_dim_dynamic(block, n,
                                                      padded_batch):
                f = f[:, :orig_batch]
            out.append(f)
        return tuple(out)

    def _has_longer_scan(self, miss_key, k):
        """True when a scan with the same per-step signature but MORE
        steps is already compiled — i.e. this call is the short final
        chunk of a steady run_steps loop."""
        tag, fp, feed_sig, fetch_names, state_names = miss_key

        def strip_k(sig):
            return tuple((n, tuple(s[1:]), dt) for n, s, dt in sig)

        want = strip_k(feed_sig)
        for key in self._cache:
            if len(key) != 5 or key[0] != tag or key[1] != fp \
                    or key[3] != fetch_names or key[4] != state_names:
                continue
            ks = {int(s[0]) for _, s, _ in key[2] if len(s) >= 1}
            if len(ks) == 1 and ks.pop() > k and strip_k(key[2]) == want:
                return True
        return False

    def _run_steps_fallback(self, program, feed_vals, k, fetch_list,
                            scope, return_numpy):
        """Serve a K' < K final chunk as K' single-step dispatches through
        run() (whose own cache/bucketing applies) and restack the
        fetches to the run_steps [K', ...] contract."""
        outs = []
        for i in range(k):
            outs.append(self.run(
                program, feed={n: v[i] for n, v in feed_vals.items()},
                fetch_list=fetch_list, scope=scope, return_numpy=True))
        n_fetch = len(outs[0]) if outs else 0
        stacked = [np.stack([o[j] for o in outs]) for j in range(n_fetch)]
        if return_numpy:
            return stacked
        return [jnp.asarray(s) for s in stacked]

    # -- prefetch-driven step loop ------------------------------------------
    def run_prefetched(self, program, feeds, fetch_list=None, scope=None,
                       return_numpy=True, prefetch_depth=2):
        """Generator over `feeds` (an iterable of feed dicts) with async
        double-buffered device placement: batch N+1's `device_put` rides a
        worker thread while batch N computes (reader/prefetcher.py).
        Yields each step's fetch list — iterate it to drive the loop:

            for out in exe.run_prefetched(main, batches, fetch_list=[loss]):
                ...

        Feeds arriving as `jax.Array` (already placed) pass through the
        placement stage untouched, so staged and host batches can mix."""
        from ..reader.prefetcher import Prefetcher
        pf = Prefetcher(feeds, depth=prefetch_depth)
        self._active_prefetcher = pf
        try:
            for feed in pf:
                yield self.run(program, feed=feed, fetch_list=fetch_list,
                               scope=scope, return_numpy=return_numpy)
        finally:
            self._active_prefetcher = None
            pf.close()

    # -- checkpointing (paddle_tpu/checkpoint, docs/checkpoint.md) ----------
    def enable_checkpointing(self, manager, program=None, every_n_steps=100,
                             scope=None, barrier=None):
        """Periodic async checkpoints of `program`'s persistable state.

        After every run()/run_steps() that advances ``self._step`` across
        an ``every_n_steps`` boundary, the persistables (params AND
        optimizer accumulators — in static mode both live in the scope),
        the executor step, and the RNG state are snapshotted and handed
        to `manager` for background persistence.  Also registers the
        manager's preemption state provider, so a SIGTERM final save
        captures the live state (CheckpointManager.
        install_preemption_handler).

        With ``program=None`` the hook binds to the first TRAINING
        program (one containing gradient/optimizer ops) run after
        enabling; startup and eval programs running through the same
        executor neither trigger saves nor hijack the snapshot.

        With a ``world_size > 1`` manager, `barrier` (e.g.
        ``paddle_tpu.distributed.collective.barrier``) lets the hook
        publish each staged checkpoint during the run: save → wait →
        barrier → rank-0 commit.  Without one, stages stay pending until
        the next rank-0 startup recovers them."""
        if every_n_steps < 1:
            raise ValueError("every_n_steps must be >= 1")
        self._ckpt = _CkptHook(manager=manager, program=program,
                               every=int(every_n_steps), scope=scope,
                               last=self._step)
        self._ckpt_barrier = barrier
        if getattr(manager, "world_size", 1) > 1 and barrier is None:
            import warnings
            warnings.warn(
                "multi-host CheckpointManager without barrier=: periodic "
                "checkpoints are only STAGED during the run and get "
                "committed at the next rank-0 startup; pass barrier= "
                "(e.g. paddle_tpu.distributed.collective.barrier) to "
                "publish them as training goes", RuntimeWarning,
                stacklevel=2)
        def _provider():
            # prefer the (possibly latched) registered program and the
            # scope training actually runs in, so the final preemption
            # save snapshots the same state the periodic hook does —
            # the enable-time scope may be None while every run passes
            # an explicit one
            hook = self._ckpt
            prog = (hook.program if hook else None) or program
            sc = (hook.scope or hook.run_scope) if hook else scope
            return self.checkpoint_snapshot(prog, sc)

        manager.set_state_provider(_provider)

    def disable_checkpointing(self):
        if self._ckpt is not None:
            # also detach the preemption provider: a SIGTERM after an
            # explicit disable must not commit a snapshot of whatever
            # default_main_program() happens to be
            self._ckpt.manager.set_state_provider(None)
        self._ckpt = None

    def checkpoint_snapshot(self, program=None, scope=None):
        """(step, state, extra) for CheckpointManager.save: persistable
        scope values + executor step + RNG + dataset position (when a
        run_prefetched loop is active)."""
        program = program or default_main_program()
        # CompiledProgram / ParallelExecutor wrap the real Program
        program = _unwrap_program(program)
        scope = scope or global_scope()
        state = {n: scope.get(n) for n in _persistable_names(program)
                 if scope.get(n) is not None}
        from ..core.generator import get_rng_state
        extra = {"executor_step": self._step, "rng": get_rng_state(),
                 "program_fingerprint": program.fingerprint()}
        # topology-shift sidecars: enough for restore_from_checkpoint to
        # convert layouts and re-derive schedule counters when the next
        # incarnation of this job runs at a different world size
        plan = getattr(program, "_zero_shard_plan", None)
        if plan is not None and getattr(plan, "buckets", None):
            extra["zero_shard_plan"] = plan.to_dict()
            extra["dp_degree"] = int(plan.dp_degree)
        el = getattr(program, "_elastic_meta", None)
        if el is not None:
            cnt = scope.get(el["counter"])
            extra["elastic"] = {
                "logical_dp": int(el["logical_dp"]),
                "k": int(getattr(self, "_last_elastic_k", 1)),
                "world": int(getattr(self, "_last_elastic_world", 1)),
                "counter": el["counter"], "accs": list(el["accs"]),
                # the program's own persistable micro counter is the
                # authoritative schedule position (executor _step also
                # counts startup/eval runs)
                "counter_value": int(np.asarray(cnt).reshape(-1)[0])
                if cnt is not None else self._elastic_steps}
        gm = getattr(program, "_gm_meta", None)
        if gm is not None:
            extra["gradient_merge"] = dict(gm)
        pf = self._active_prefetcher
        if pf is not None:
            extra["dataset_position"] = pf.position
        return self._step, state, extra

    def _maybe_checkpoint(self, program, scope):
        hook = self._ckpt
        if hook is None:
            return
        run_p = _unwrap_program(program)
        if hook.program is None:
            # bind to the first TRAINING program run after enabling —
            # runs of the startup or an eval program must neither latch
            # (that would silently disable checkpointing of the real
            # train loop) nor be snapshotted (their persistables lack
            # the optimizer accumulators, and restoring such a
            # checkpoint would silently reset Adam moments)
            if not (isinstance(run_p, Program) and _is_training(run_p)):
                return
            hook.program = run_p
        # compare the underlying Programs: registering the raw Program
        # but running it through CompiledProgram / ParallelExecutor (the
        # multi-chip paths) must still checkpoint
        if run_p is not _unwrap_program(hook.program):
            return
        # remember where the registered program actually runs — the
        # preemption provider snapshots this scope when none was given
        # at enable time
        hook.run_scope = scope
        if self._step - hook.last < hook.every:
            return
        step, state, extra = self.checkpoint_snapshot(
            hook.program, hook.scope or scope)
        hook.manager.save(step, state, extra=extra)
        if getattr(hook.manager, "world_size", 1) > 1 and \
                self._ckpt_barrier is not None:
            # multi-host publish: every rank staged+fsync'd, then rank 0
            # renames — never publishes a stage another rank is writing
            hook.manager.wait()
            self._ckpt_barrier()
            hook.manager.commit(step)
        hook.last = self._step

    def restore_from_checkpoint(self, manager, program=None, scope=None,
                                step=None, world=None,
                                on_mismatch="convert"):
        """Auto-resume: load the newest VALID checkpoint (corrupt ones are
        skipped by the manager), write the state back into the scope, and
        restore the executor step + RNG so per-step derived seeds replay
        identically.  Returns the restored step, or None when the
        checkpoint root is empty (fresh start).

        Topology-shifted resume (docs/elastic.md): when the checkpoint's
        program fingerprint differs from `program`'s because the
        data-parallel world changed, the state is CONVERTED instead of
        loaded as a chimera:

          * ZeRO-1 shard-count mismatch — the checkpoint's recorded
            ``ShardingPlan`` routes the bucket slots through
            ``sharding.unshard_state`` → ``sharding.reshard_state`` for
            the target program's plan (either side may also be plain);
          * elastic programs (``distributed.elastic``) fingerprint
            identically across worlds; their micro-step counter and
            executor step are re-derived for the new K = N/world
            (``world`` defaults to every local device, the same default
            mesh CompiledProgram builds);
          * ``gradient_merge`` counters are re-denominated when the
            resumed program uses a different k; a mid-window position
            rounds down to the last commit and replays the window.

        ``on_mismatch``: "convert" (default) converts when it can and
        warns otherwise; "error" raises ``CheckpointError`` on any
        unconvertible fingerprint mismatch; "warn" restores the old
        chimera behaviour with a warning only.

        The checkpoint's non-tensor sidecar survives on
        ``self.last_restored_extra`` — in particular
        ``extra["dataset_position"]`` (batches already consumed by the
        interrupted run_prefetched loop; on an elastic shift it is
        re-derived to GLOBAL batches, the unit `rebucket_feeds`
        consumes), which the caller uses to fast-forward its feed
        source::

            pos = (exe.last_restored_extra or {}).get("dataset_position", 0)
            for out in exe.run_prefetched(main, islice(feeds, pos, None)):
                ...
        """
        import warnings
        if on_mismatch not in ("convert", "error", "warn"):
            raise ValueError(
                f"on_mismatch must be 'convert', 'error' or 'warn', "
                f"got {on_mismatch!r}")
        # the manager owns the STORAGE-layer topology shift (the
        # checkpoint was written by a different rank count): forward
        # on_mismatch so 'convert' routes through the rank-merged loader
        # and 'error' names both worlds (duck-typed managers in tests
        # may not take the kwarg)
        import inspect
        load_kwargs = {"step": step}
        try:
            if "on_mismatch" in inspect.signature(
                    manager.load).parameters:
                load_kwargs["on_mismatch"] = on_mismatch
        except (TypeError, ValueError):
            pass
        ckpt = manager.load(**load_kwargs)
        if ckpt is None:
            self.last_restored_extra = None
            return None
        scope = scope or global_scope()
        extra = dict(ckpt.extra)
        state = dict(ckpt.state)
        target = _unwrap_program(program) if program is not None else None
        saved_fp = extra.get("program_fingerprint")
        if target is not None and saved_fp is not None and \
                target.fingerprint() != saved_fp:
            state = self._convert_topology_shift(
                state, extra, target, on_mismatch)
        for name, val in state.items():
            # jnp.array (copy), never jnp.asarray: a zero-copy alias of
            # host memory would be donated to XLA by the next step's
            # donate_argnums and freed/reused out from under numpy
            scope.set(name, jnp.array(val))
        self._step = int(extra.get("executor_step", ckpt.step))
        # schedule re-derivation: elastic K and gradient-merge k counters
        # are denominated in micro-steps whose meaning changes with the
        # world / the rebuilt program
        self._rederive_elastic(target, scope, extra, world)
        self._rederive_gradient_merge(target, scope, extra, warnings)
        if self._ckpt is not None:
            # enable-then-restore ordering: re-anchor the last-saved
            # marker so the next run doesn't immediately re-save the
            # state just loaded (and shift every later boundary)
            self._ckpt.last = self._step
        if "rng" in extra:
            from ..core.generator import set_rng_state
            set_rng_state(extra["rng"])
        self.last_restored_extra = dict(extra)
        from ..observability.journal import emit as _jemit
        _jemit("restore", step=int(ckpt.step),
               executor_step=int(self._step),
               global_step=extra.get("global_step"))
        return ckpt.step

    def _convert_topology_shift(self, state, extra, target, on_mismatch):
        """Fingerprint mismatch triage: convert ZeRO-1 layouts when the
        plans are recorded, otherwise warn (or raise under 'error')."""
        import warnings
        saved_plan = extra.get("zero_shard_plan")
        tgt_plan = getattr(target, "_zero_shard_plan", None)
        if tgt_plan is not None and not getattr(tgt_plan, "buckets", None):
            tgt_plan = None
        if saved_plan or tgt_plan is not None:
            from ..distributed.sharding import (reshard_state,
                                                unshard_state)
            src_dp = (saved_plan or {}).get("dp_degree", 1)
            tgt_dp = tgt_plan.dp_degree if tgt_plan is not None else 1
            try:
                converted = state
                if saved_plan:
                    converted = unshard_state(converted, saved_plan)
                if tgt_plan is not None:
                    converted = reshard_state(converted, tgt_plan)
            except (ValueError, KeyError) as e:
                # colliding names with different shapes etc. — the two
                # programs differ beyond their shard layout and the
                # relayout itself is impossible
                if on_mismatch == "error":
                    from ..checkpoint import CheckpointError
                    raise CheckpointError(
                        "fingerprint mismatch is not a pure ZeRO-1 "
                        f"shard-count change (layout conversion failed: "
                        f"{e}) — refusing the chimera restore "
                        "(on_mismatch='error')") from e
                warnings.warn(
                    "restoring a checkpoint saved from a DIFFERENT "
                    f"program (fingerprint mismatch): ZeRO-1 layout "
                    f"conversion dp={src_dp} -> dp={tgt_dp} FAILED "
                    f"({e}); loading the unconverted state — resumed "
                    "training may diverge (pass on_mismatch='error' "
                    "to refuse)", RuntimeWarning, stacklevel=3)
                return state
            state = converted
            # a PURE shard-count shift converts completely: every target
            # persistable is in the converted state.  Leftover holes mean
            # the programs differ beyond sharding — that is still a
            # chimera, and 'error' must refuse it even though a plan
            # existed
            missing = [n for n in _persistable_names(target)
                       if n not in state]
            if missing:
                if on_mismatch == "error":
                    from ..checkpoint import CheckpointError
                    raise CheckpointError(
                        "fingerprint mismatch is not a pure ZeRO-1 "
                        "shard-count change: after layout conversion "
                        f"the checkpoint still lacks {missing[:8]}"
                        f"{'...' if len(missing) > 8 else ''} — "
                        "refusing the chimera restore "
                        "(on_mismatch='error')")
                warnings.warn(
                    "restoring a checkpoint saved from a DIFFERENT "
                    "program (fingerprint mismatch): converted the "
                    f"ZeRO-1 layout dp={src_dp} -> dp={tgt_dp}, but "
                    f"{len(missing)} target vars are still absent and "
                    "keep their fresh-init values — resumed training "
                    "may diverge (pass on_mismatch='error' to refuse)",
                    RuntimeWarning, stacklevel=3)
                return state
            warnings.warn(
                "restoring a checkpoint saved from a DIFFERENT program "
                "(fingerprint mismatch): automatically converted the "
                f"ZeRO-1 optimizer-state layout dp={src_dp} -> "
                f"dp={tgt_dp} (unshard_state -> reshard_state); "
                "training resumes on the re-bucketed state",
                RuntimeWarning, stacklevel=3)
            return state
        if on_mismatch == "error":
            from ..checkpoint import CheckpointError
            raise CheckpointError(
                "checkpoint program fingerprint does not match the "
                "target program and no recorded sharding plan makes the "
                "difference convertible; pass on_mismatch='warn' to "
                "force the (diverging) chimera restore")
        warnings.warn(
            "restoring a checkpoint saved from a DIFFERENT "
            "program (fingerprint mismatch): vars absent from "
            "the checkpoint keep their fresh-init values and "
            "orphan checkpoint vars are still written — resumed "
            "training may diverge from the original run "
            "(pass on_mismatch='error' to refuse chimera loads)",
            RuntimeWarning, stacklevel=3)
        return state

    def _rederive_elastic(self, target, scope, extra, world):
        """Elastic schedule position -> the new world's denomination."""
        el_meta = getattr(target, "_elastic_meta", None) \
            if target is not None else None
        if el_meta is None or "elastic" not in extra:
            return
        import jax as _jax
        from ..distributed.elastic import rederive_schedule
        new_world = int(world) if world else len(_jax.devices())
        red = rederive_schedule(extra, new_world)
        if red is None:
            return
        self._step = red["executor_step"]
        self._elastic_steps = red["executor_step"]
        self._last_elastic_k = red["k_new"]
        self._last_elastic_world = new_world
        # CompiledProgram re-anchors for its ACTUAL mesh on first run —
        # `world` here is only the best-effort default (all devices)
        self._elastic_rebase_global = red["global_step"]
        scope.set(el_meta["counter"],
                  jnp.array(np.full((1,), red["counter_value"], np.int32)))
        if red["replayed_micro"]:
            for acc in el_meta["accs"]:
                v = scope.get(acc)
                if v is not None:
                    scope.set(acc, jnp.zeros_like(jnp.asarray(v)))
        if "dataset_position" in extra:
            # GLOBAL batches, not micro-feeds: the elastic feeding
            # pattern is rebucket_feeds over global batches, and the
            # actual mesh (hence K) may differ from the `world` default
            # used here — a K-denominated position would go stale the
            # moment CompiledProgram re-anchors for its real mesh
            extra["dataset_position"] = red["global_batches_consumed"]
        extra["global_step"] = red["global_step"]

    def _rederive_gradient_merge(self, target, scope, extra, warnings):
        """gradient_merge counter k_old -> k_new re-denomination (global
        batch preserved across a world change by scaling k)."""
        tgt_gm = getattr(target, "_gm_meta", None) \
            if target is not None else None
        saved_gm = extra.get("gradient_merge")
        if tgt_gm is None or not saved_gm:
            return
        k_old = max(1, int(saved_gm.get("k", 1)))
        k_new = max(1, int(tgt_gm.get("k", 1)))
        same_names = saved_gm.get("counter") == tgt_gm.get("counter")
        if k_old == k_new and same_names:
            return  # identical schedule: restored state is already right
        cnt = scope.get(saved_gm.get("counter")) \
            if saved_gm.get("counter") else None
        old_count = int(np.asarray(cnt).reshape(-1)[0]) \
            if cnt is not None else 0
        commits, j = divmod(old_count, k_old)
        if j:
            warnings.warn(
                f"gradient_merge resume mid-window (micro {j}/{k_old}): "
                f"rounding down to commit {commits}; the partial window "
                "replays and its accumulators are reset", RuntimeWarning,
                stacklevel=3)
        scope.set(tgt_gm["counter"],
                  jnp.array(np.full((1,), commits * k_new, np.int32)))
        for acc in tgt_gm.get("accs", []):
            v = scope.get(acc)
            if v is not None and (j or not same_names):
                scope.set(acc, jnp.zeros_like(jnp.asarray(v)))
        if "dataset_position" in extra:
            # the discarded j mid-window micro-batches must REPLAY, not
            # be skipped: re-derive the feed position to the commit
            # boundary in the NEW k's denomination (one batch per
            # micro-step), like the elastic path does
            extra["dataset_position"] = commits * k_new

    # -- helpers ------------------------------------------------------------
    def _coerce_feed(self, block, name, val):
        # x64-disabled backends (the TPU default) cannot hold 64-bit
        # values: canonicalize on the HOST side before jnp sees the array
        # — jnp.asarray(int64) emits a per-call truncation UserWarning and
        # an extra device-side cast otherwise.  Shared dtype table with
        # the prefetched path (core.dtype.canonical_np_dtype) so both
        # produce the same jit signature.
        from ..core.dtype import canonical_np_dtype
        import jax as _jax
        x64 = bool(_jax.config.jax_enable_x64)
        if not isinstance(val, _jax.Array):
            a = np.asarray(val)
            tgt = canonical_np_dtype(a.dtype, x64)
            val = a if tgt == a.dtype else a.astype(tgt)
        arr = jnp.asarray(val)
        try:
            var = block.var(name)
        except KeyError:
            return arr
        want = var.dtype
        if want is None or str(arr.dtype) == want:
            return arr
        tgt = canonical_np_dtype(np_dtype(want), x64)
        if arr.dtype != tgt:
            arr = arr.astype(tgt)
        return arr

    def _seed_for_step(self, program: Program) -> int:
        return (int(program.random_seed) * 1000003 + self._step) % (2 ** 31)

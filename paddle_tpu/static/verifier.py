"""Program IR verifier & distributed-correctness analyzer.

Analog of the reference's pre-execution validation
(/root/reference/paddle/fluid/framework/op_desc.cc OpDesc::Check +
per-op InferShape run by the C++ executor before launch) — but widened
to the invariants that actually break THIS framework: paddle_tpu stacks
five interacting program-rewrite passes (AMP, recompute, gradient_merge,
ZeRO-1 sharding, elastic fold) whose composition contracts were, until
now, enforced only by convention and caught only when an 8-device run
deadlocked or diverged.  This module moves those failures from tunnel
time to compile time, the same way `static/memory_analysis.py` moved
OOMs to estimator time.

`check_program(program, level=...)` walks the op IR and reports
structured `Diagnostic`s (never raises on a defect unless asked) at five
cumulative levels:

  1. ``graph``       — def-before-use, dangling vars, dtype/shape
                       consistency (via the same abstract evaluation as
                       `core/infer_shape.py`), feed/fetch/persistable
                       integrity, duplicate-write (SSA violation)
                       detection outside known accumulator patterns.
  2. ``collective``  — the SPMD/distributed checker: extracts the
                       ordered collective sequence, verifies
                       ring_id/dp_degree/shape/dtype agreement,
                       reduce-scatter↔allgather pairing, `dp_shard`
                       metadata consistency, control-flow-divergent
                       collectives (a collective under a data-dependent
                       sub-block = a guaranteed cross-rank deadlock
                       under shard_map), psum-reassociation hazards in
                       bitwise-order fold paths, double reductions, and
                       pass-composition order (the applied-passes
                       registry, `core/pass_framework.py`).
  3. ``donation``    — buffers donated to XLA (ZeRO slot shards,
                       elastic accumulators, the jitted step's donated
                       persistable state): alias-creating startup
                       assigns (double donation), reads-after-donation
                       (a forward/backward-role op reading state an
                       optimizer-role op already committed), fetches of
                       per-rank shards.
  4. ``retrace``     — lint for feeds whose shapes escape the batch-dim
                       bucketing policy and Python-captured array
                       constants baked into op attrs (each build
                       fingerprints differently → retrace every step).
  5. ``layout``      — the sharding-propagation analyzer
                       (static/layout_analysis.py): whole-graph SPMD
                       layout inference over the dp × mp mesh, V601-V605
                       (layout conflicts, missing reductions, redundant
                       reshards, mesh-axis disagreements, indivisible
                       shards) plus the priced reshard table.

Diagnostic codes are STABLE (docs/static_analysis.md): tests and
allowlists key on them.  Every diagnostic carries provenance (block/op
index, op type, op_uid, var name) so a report names the defect site,
not just the defect class.

`collective_sequence(program)` / `collective_wire_bytes(program, world)`
expose the ordered collective schedule and its ring-algorithm ICI cost —
the shared substrate the ROADMAP auto-parallel planner needs for
wire-byte costing.

Gating: ``PADDLE_TPU_VERIFY`` env ("" = off, "warn", "strict") arms
(a) a first-compile hook in `static/executor.py` /
`distributed/compiled_program.py` and (b) post-rewrite self-checks in
every rewrite pass; "strict" raises `ProgramVerificationError` on any
error-severity diagnostic.
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.program import Block, OpDesc, OpRole, Program

__all__ = [
    "Diagnostic", "VerifyReport", "ProgramVerificationError",
    "check_program", "collective_sequence", "collective_wire_bytes",
    "entry_wire_bytes", "collective_wire_bytes_by_axis", "ring_axis",
    "program_ring_degrees",
    "verify_mode", "self_check", "verify_first_compile", "VERIFY_ENV",
]

VERIFY_ENV = "PADDLE_TPU_VERIFY"

# level name -> highest suite number it runs (levels are cumulative);
# 5 = the sharding-propagation layout analyzer (layout_analysis.py V6xx)
_LEVELS = {"graph": 1, "collective": 2, "donation": 3, "retrace": 4,
           "layout": 5, "all": 5, "strict": 5}

ERROR = "error"
WARNING = "warning"


class ProgramVerificationError(RuntimeError):
    """Raised by strict-mode verification when error diagnostics exist."""

    def __init__(self, report: "VerifyReport", context: str = ""):
        self.report = report
        head = f"program verification failed ({context})" if context \
            else "program verification failed"
        super().__init__(f"{head}:\n{report.render(errors_only=True)}")


class Diagnostic:
    """One structured finding with provenance."""

    __slots__ = ("code", "severity", "message", "block_idx", "op_idx",
                 "op_type", "op_uid", "var")

    def __init__(self, code: str, severity: str, message: str,
                 block_idx: Optional[int] = None,
                 op_idx: Optional[int] = None,
                 op_type: Optional[str] = None,
                 op_uid: Optional[int] = None,
                 var: Optional[str] = None):
        self.code = code
        self.severity = severity
        self.message = message
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.op_uid = op_uid
        self.var = var

    def where(self) -> str:
        parts = []
        if self.block_idx is not None:
            parts.append(f"block {self.block_idx}")
        if self.op_idx is not None:
            parts.append(f"op {self.op_idx}")
        if self.op_type:
            uid = f" uid={self.op_uid}" if self.op_uid is not None else ""
            parts.append(f"{self.op_type!r}{uid}")
        if self.var:
            parts.append(f"var {self.var!r}")
        return ", ".join(parts)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        w = self.where()
        return f"[{self.code}/{self.severity}] {self.message}" + \
            (f"  ({w})" if w else "")


class VerifyReport:
    """All diagnostics from one `check_program` run."""

    def __init__(self, diagnostics: List[Diagnostic], level: str,
                 applied_passes: Optional[List[dict]] = None):
        self.diagnostics = list(diagnostics)
        self.level = level
        self.applied_passes = list(applied_passes or [])

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def render(self, errors_only: bool = False) -> str:
        ds = self.errors if errors_only else self.diagnostics
        if not ds:
            return "clean (0 diagnostics)"
        return "\n".join(f"  {d!r}" for d in ds)

    def raise_on_error(self, context: str = ""):
        if self.errors:
            raise ProgramVerificationError(self, context)
        return self

    def __repr__(self):
        return (f"VerifyReport(level={self.level!r}, "
                f"errors={len(self.errors)}, "
                f"warnings={len(self.warnings)})")


# ---------------------------------------------------------------------------
# op-type classification tables
# ---------------------------------------------------------------------------
# cross-rank communication ops: must execute in the same order with the
# same operands on every rank or the mesh deadlocks / diverges
_COLLECTIVE_OPS = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_reducescatter", "c_allgather", "c_broadcast",
    "broadcast", "c_scatter", "c_concat", "c_split", "alltoall",
    "barrier", "mp_allreduce_sum", "c_elastic_fold", "partial_allgather",
    "p_send", "p_recv", "ring_attention", "sync_batch_norm",
    "sync_batch_norm_grad",
    # the Megatron f-operator's BACKWARD is an allreduce over the tensor
    # ring (ops/kernels/collective._c_identity_grad); grad ops inherit
    # the forward op's ring/mp stamps, so the schedule and the per-axis
    # wire pricer both see the mp ring's dominant backward cost
    "c_identity_grad",
))

# collectives whose summation order XLA may legally reassociate — fatal
# inside a path that requires a bitwise-stable reduction order (the
# elastic fold's whole contract, distributed/elastic.py)
_PSUM_ORDER_SENSITIVE = frozenset((
    "c_allreduce_sum", "c_reducescatter", "mp_allreduce_sum",
))

# output shapes depend on the mesh (off-mesh the kernels degrade to
# identity), so the abstract-evaluation shape check must skip them
_MESH_DEPENDENT_OPS = frozenset((
    "c_reducescatter", "c_allgather", "c_split", "c_concat", "c_scatter",
    "alltoall", "partial_allgather", "c_elastic_fold",
    "elastic_commit_mask", "scale_by_world_size", "ring_attention",
    "p_send", "p_recv",
))

# control-flow container ops: their sub-block carries run under traced
# lax control flow, where per-rank divergence is possible
_CONTROL_FLOW_OPS = frozenset((
    "while", "conditional_block", "cond", "static_rnn", "recurrent",
))

# in-place container writers: a tensor array var IS rebound by every
# write (write_to_array at index i), so multi-write is its contract,
# not an SSA violation
_INPLACE_CONTAINER_OPS = frozenset((
    "write_to_array", "array_write", "lod_tensor_to_array",
    "create_tensor_array",
))

# ops a reduction pass inserts between a collective and its consumer —
# shared vocabulary with distributed/compiled_program._grad_already_reduced
_REDUCE_TRANSPARENT = frozenset((
    "scale_by_world_size", "scale", "cast", "elementwise_add", "where",
    "reshape", "reshape2", "concat", "pad", "slice", "assign",
    "check_finite_and_unscale", "update_loss_scaling",
))
_REDUCE_OPS = frozenset(("c_allreduce_sum", "c_reducescatter",
                         "c_elastic_fold"))

_STARTUP_INIT_OPS = frozenset((
    "fill_constant", "uniform_random", "gaussian_random",
    "truncated_gaussian_random", "assign_value", "eye", "c_broadcast",
    "broadcast", "seed", "range", "linspace", "scale", "assign",
))


def _role(op: OpDesc) -> int:
    return int(op.attrs.get(OpRole.KEY, OpRole.Forward))


def _is_optimize_write(op: OpDesc) -> bool:
    return bool(_role(op) & OpRole.Optimize)


def _is_fwd_bwd_read(op: OpDesc) -> bool:
    # strip the Loss marker bit; Forward(0) and Backward(1) remain
    return (_role(op) & ~OpRole.Loss) in (OpRole.Forward, OpRole.Backward)


def _var_of(block: Block, name: str):
    try:
        return block.var(name)
    except KeyError:
        return None


def _numel(shape) -> Optional[int]:
    if shape is None:
        return None
    n = 1
    for d in shape:
        d = int(d)
        if d < 0:
            return None
        n *= d
    return n


def _dtype_bytes(dtype: Optional[str]) -> int:
    if not dtype:
        return 0
    from ..core.dtype import np_dtype
    try:
        return int(np.dtype(np_dtype(dtype)).itemsize)
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# collective-sequence extraction (the planner's wire-cost substrate)
# ---------------------------------------------------------------------------
def collective_sequence(program: Program) -> List[dict]:
    """The ordered cross-rank communication schedule of `program`.

    One entry per collective op, in execution order, with the operand
    metadata every rank must agree on (this IS the deadlock surface:
    under shard_map each rank traces the same op list, so any divergence
    in order/ring/shape means a rank waits on a collective its peers
    never post).  Entry keys: ``block``/``index`` (provenance),
    ``type``, ``ring_id``, ``dp_degree`` (None unless stamped),
    ``var``/``shape``/``dtype``/``nbytes`` (the X operand), ``op_uid``.

    This is also the substrate the ROADMAP auto-parallel planner costs
    ICI wire bytes over — see `collective_wire_bytes`.
    """
    seq = []
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            if op.type not in _COLLECTIVE_OPS:
                continue
            xnames = op.inputs.get("X", []) or op.input_names()
            xname = xnames[0] if xnames else None
            v = _var_of(block, xname) if xname else None
            shape = tuple(v.shape) if v is not None and v.shape is not None \
                else None
            dtype = v.dtype if v is not None else None
            numel = _numel(shape)
            seq.append({
                "block": block.idx, "index": i, "type": op.type,
                "ring_id": int(op.attrs.get("ring_id", 0)),
                "dp_degree": (int(op.attrs["dp_degree"])
                              if op.attrs.get("dp_degree") else None),
                "var": xname, "shape": shape, "dtype": dtype,
                "nbytes": (numel * _dtype_bytes(dtype)
                           if numel is not None else None),
                "op_uid": op.attrs.get("op_uid"),
                # ZeRO stage stamps (distributed/sharding.py): stage the
                # pass emitted this op for and its role in the bucket
                # chain — the stage-aware pairing checks and the wire
                # pricer both read them
                "zero_stage": op.attrs.get("zero_stage"),
                "zero_role": op.attrs.get("zero_role"),
                # the X operand is a dp_shard persistable declared at the
                # GLOBAL padded shape: each rank's LOCAL operand is
                # 1/degree of the declared bytes (ZeRO-3 param gathers)
                "x_dp_shard": (int(v.attrs.get("dp_shard") or 0)
                               if v is not None else 0),
                # tensor-parallel builder stamps (distributed/
                # tensor_parallel.py): the model axis the op rides and
                # the tp degree declared at build time — the per-ring
                # wire pricer uses the degree, the layout analyzer the
                # axis
                "mp_axis": op.attrs.get("mp_axis"),
                "tp_degree": (int(op.attrs["tp_degree"])
                              if op.attrs.get("tp_degree") else None),
            })
    return seq


# default ring → mesh-axis binding: the shared canonicalizer table
# (core/mesh_axes.py — the same source CompiledProgram._get_mesh and
# layout_analysis speak, so analyzer and runtime can never disagree on
# the tensor axis's name)
from ..core.mesh_axes import RING_AXIS as _RING_AXIS
from ..core.mesh_axes import canonical_axis as _canonical_axis


def ring_axis(ring_id: int, mp_axis: Optional[str] = None) -> str:
    """The CANONICAL mesh-axis name a ring id binds to (``mp_axis``
    stamp wins; runtime spellings like ``"tp"`` canonicalize through
    `core.mesh_axes`; unknown rings render as ``ring<N>``)."""
    if mp_axis:
        return _canonical_axis(str(mp_axis))
    return _RING_AXIS.get(int(ring_id), f"ring{int(ring_id)}")


def _ring_degrees_from_seq(seq: List[dict]) -> Dict[int, int]:
    degrees: Dict[int, int] = {}
    for e in seq:
        d = e["tp_degree"] or e["dp_degree"]
        if d:
            degrees[e["ring_id"]] = max(degrees.get(e["ring_id"], 0),
                                        int(d))
    return degrees


def program_ring_degrees(program: Program) -> Dict[int, int]:
    """Per-ring group sizes the program's op stamps declare: the
    builders' ``tp_degree`` on the tensor ring, the sharding pass's
    ``dp_degree`` on ring 0.  The wire pricer's `ring_degrees` input —
    a non-dp ring must be priced at ITS degree, not the dp world.
    (Callers already holding a `collective_sequence` should derive the
    degrees from it instead of re-walking the program.)"""
    return _ring_degrees_from_seq(collective_sequence(program))


def _entry_nbytes(entry: dict, batch: Optional[int] = None) \
        -> Optional[int]:
    """An entry's operand bytes, optionally binding symbolic -1 dims to
    `batch`: the mp-ring collectives ride ACTIVATIONS ([-1, t, hidden]
    cotangents and partial sums), whose wire cost is batch-proportional
    and prices 0 unless the caller binds the batch."""
    n = entry.get("nbytes")
    if n:
        return n
    shape = entry.get("shape")
    if not batch or not shape:
        return None
    total = 1
    for d in shape:
        d = int(d)
        total *= int(batch) if d < 0 else d
    return total * _dtype_bytes(entry.get("dtype"))


def entry_wire_bytes(entry: dict, world: int,
                     ring_degrees: Optional[Dict[int, int]] = None,
                     batch: Optional[int] = None) -> float:
    """Ring-algorithm ICI bytes ONE rank moves for a single
    `collective_sequence` entry: allreduce 2(N-1)/N of the buffer,
    reduce-scatter (N-1)/N, allgather and the elastic all-gather fold
    (N-1)× the local shard, broadcast/scatter (N-1)/N, alltoall
    (N-1)/N.  Group-size resolution, most specific first: the entry's
    own ``dp_degree``/``tp_degree`` stamp (the pass that emitted the op
    recorded the group it rewrote for), then ``ring_degrees`` (ring id →
    size, e.g. `program_ring_degrees` or a planner's candidate mesh),
    then `world` — so a tensor-ring collective on a 4×2 mesh prices at
    its mp degree 2, never the dp world.  `batch` binds symbolic -1
    dims so activation collectives (the mp ring's whole traffic) price
    their batch-proportional bytes; unknown sizes price 0.
    Shared by `collective_wire_bytes` and the auto-parallel planner's
    overlap-aware roofline (static/planner.py)."""
    n = _entry_nbytes(entry, batch)
    if not n:
        return 0.0
    g = (entry["dp_degree"] or entry.get("tp_degree") or
         (ring_degrees or {}).get(entry["ring_id"]) or world)
    if g <= 1:
        return 0.0
    t = entry["type"]
    if t in ("c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
             "c_allreduce_prod", "mp_allreduce_sum", "sync_batch_norm",
             "sync_batch_norm_grad", "c_identity_grad"):
        # c_identity_grad: the Megatron f-operator's backward psum of
        # the replicated input's cotangent over the tensor ring
        return 2.0 * (g - 1) / g * n
    if t in ("c_reducescatter", "c_scatter", "c_broadcast",
             "broadcast", "alltoall"):
        return (g - 1) / g * n
    if t in ("c_allgather", "c_concat", "c_elastic_fold",
             "partial_allgather"):
        # input is the local shard; the ring moves (g-1) remote shards
        # (c_concat's kernel IS a tiled all_gather, ops/kernels/
        # collective.py).  When the operand is DECLARED at the GLOBAL
        # gathered shape — a ZeRO-3 dp_shard param bucket, or a
        # tensor-ring gather whose builder keeps build-time shapes
        # global (``mp_axis`` stamp) — the local shard is 1/g of the
        # declared bytes.
        if entry.get("x_dp_shard") or entry.get("mp_axis"):
            return (g - 1) / g * n
        return float((g - 1) * n)
    if t in ("p_send", "p_recv"):
        return float(n)
    # c_split is a LOCAL dynamic slice of a replicated operand (each
    # rank keeps its own piece — ops/kernels/collective.py): zero wire.
    # barrier / elastic_commit_mask / ring_attention: control traffic
    # only (ring_attention's K/V rotation is its own op-internal story).
    return 0.0


def collective_wire_bytes(program: Program, world: int,
                          ring_id: Optional[int] = None,
                          ring_degrees: Optional[Dict[int, int]] = None,
                          batch: Optional[int] = None) -> int:
    """ICI bytes ONE rank moves per step under ring-algorithm accounting
    (per-entry formulas: `entry_wire_bytes`).  Entries with unknown
    sizes contribute 0 (count them via `collective_sequence` if that
    matters; `batch` binds symbolic -1 dims so activation collectives
    price).  `ring_id=None` sums every ring; `ring_degrees` maps ring
    id → that ring's OWN group size (default: the program's stamps via
    `program_ring_degrees`) so non-dp rings never price at the dp
    world."""
    if world <= 1:
        return 0
    seq = collective_sequence(program)
    if ring_degrees is None:
        ring_degrees = _ring_degrees_from_seq(seq)
    total = 0.0
    for e in seq:
        if ring_id is not None and e["ring_id"] != ring_id:
            continue
        total += entry_wire_bytes(e, world, ring_degrees, batch)
    return int(total)


def collective_wire_bytes_by_axis(program: Program, world: int,
                                  ring_degrees: Optional[Dict[int, int]]
                                  = None,
                                  batch: Optional[int] = None
                                  ) -> Dict[str, int]:
    """Per-mesh-axis split of `collective_wire_bytes`: ring-accounted
    ICI bytes one rank moves per step, keyed by the axis each ring binds
    to (`ring_axis`: ring 0 → "dp", the tensor ring → "mp", the
    sequence ring → "sp").  The 2-D planner's wire substrate — an
    mp-ring byte overlaps different hardware links than a dp-ring byte,
    so the roofline must see them separately; also surfaced in the
    ``bench.py --dp-shard`` / ``--tp`` JSON.  `batch` binds symbolic -1
    dims (the mp ring's traffic is activations)."""
    seq = collective_sequence(program)
    if ring_degrees is None:
        ring_degrees = _ring_degrees_from_seq(seq)
    totals: Dict[str, float] = {}
    if world <= 1 and not ring_degrees:
        return {}
    for e in seq:
        axis = ring_axis(e["ring_id"], e.get("mp_axis"))
        totals[axis] = totals.get(axis, 0.0) + \
            entry_wire_bytes(e, world, ring_degrees, batch)
    return {a: int(b) for a, b in sorted(totals.items())}


# ---------------------------------------------------------------------------
# suite 1: graph verifier
# ---------------------------------------------------------------------------
def _check_graph(program: Program, fetch_roots: Set[str],
                 out: List[Diagnostic]):
    from ..ops.registry import get_op_info
    block = program.global_block()

    # V109 unknown ops (all blocks): the executor would hit the same
    # NotImplementedError mid-trace; catching it here names the op site
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            if op.type in ("feed", "fetch"):
                continue
            if get_op_info(op.type) is None:
                out.append(Diagnostic(
                    "V109", ERROR,
                    f"op type {op.type!r} has no registered kernel",
                    block_idx=b.idx, op_idx=i, op_type=op.type,
                    op_uid=op.attrs.get("op_uid")))

    # availability walk over the global block (sub-blocks close over the
    # whole parent env at trace time, so def-before-use is only
    # well-defined at the top level)
    available: Set[str] = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.persistable or v.is_data:
                available.add(v.name)
    def _required_inputs(op: OpDesc) -> List[str]:
        """Input names excluding OPTIONAL slots: the tracer hands a
        kernel None for a missing optional operand by contract (e.g.
        heter_recv's Dummy dependency token), so only required slots
        constitute a real read."""
        info = get_op_info(op.type)
        if info is None:
            return op.input_names()
        names = []
        for slot in info.inputs:
            if slot.optional:
                continue
            names.extend(op.inputs.get(slot.name, []))
        # names in slots the registry doesn't declare still count
        declared = {s.name for s in info.inputs}
        for slot_name, vs in op.inputs.items():
            if slot_name not in declared:
                names.extend(vs)
        return names

    writers: Dict[str, List[Tuple[int, OpDesc]]] = {}
    for i, op in enumerate(block.ops):
        if op.type == "feed":
            available.update(op.output_names())
            continue
        if op.type != "fetch":
            for n in _required_inputs(op):
                if n and n not in available and not block.has_var(n):
                    # read of a name that is neither produced, declared,
                    # persistable, nor a feed — the trace would KeyError
                    out.append(Diagnostic(
                        "V101", ERROR,
                        f"op reads {n!r} before any definition (not a "
                        f"feed, not persistable, no producing op)",
                        block_idx=0, op_idx=i, op_type=op.type,
                        op_uid=op.attrs.get("op_uid"), var=n))
                elif n and n not in available:
                    # declared but never produced: only an error when it
                    # cannot be fed (a declared non-data temp with no
                    # producer is a broken rewrite)
                    v = _var_of(block, n)
                    if v is not None and not v.is_data and \
                            not v.persistable:
                        out.append(Diagnostic(
                            "V101", ERROR,
                            f"op reads {n!r} before its definition — "
                            f"declared but no earlier op produces it",
                            block_idx=0, op_idx=i, op_type=op.type,
                            op_uid=op.attrs.get("op_uid"), var=n))
        for n in op.output_names():
            if not n:
                continue
            available.add(n)
            writers.setdefault(n, []).append((i, op))

    # V106 duplicate write (SSA violation) outside accumulator patterns:
    # persistables are the sanctioned in-place state (counters, params,
    # masked commits); control-flow carries are rewritten in place by
    # design; everything else must be single-assignment
    for n, ws in writers.items():
        if len(ws) < 2:
            continue
        v = _var_of(block, n)
        if v is not None and (v.persistable or v.is_data):
            continue
        if any(op.type in _CONTROL_FLOW_OPS or
               op.type in _INPLACE_CONTAINER_OPS for _, op in ws):
            continue
        i, op = ws[1]
        out.append(Diagnostic(
            "V106", WARNING,
            f"non-persistable var {n!r} is written by {len(ws)} ops "
            f"(SSA violation outside the known accumulator patterns); "
            f"later reads silently see the last write",
            block_idx=0, op_idx=i, op_type=op.type,
            op_uid=op.attrs.get("op_uid"), var=n))

    # V102 dangling @GRAD vars.  Scoped to gradients in a TRAINING
    # program (one with optimizer ops): there every produced gradient
    # must reach an optimizer/reduction consumer, so a dead one means a
    # rewrite dropped the consumer.  Deliberately NOT a general
    # dead-code lint — unfetched forward metrics and `gradients()` API
    # leaves are user intent (and DCE's job), not defects.
    consumed: Set[str] = set()
    for b in program.blocks:
        for op in b.ops:
            consumed.update(n for n in op.input_names() if n)
    has_optimizer = any(_is_optimize_write(op) and "Grad" in op.inputs
                        for op in block.ops)
    if has_optimizer:
        for i, op in enumerate(block.ops):
            if op.type in ("feed", "fetch"):
                continue
            info = get_op_info(op.type)
            if info is not None and info.side_effect:
                continue
            outs = [n for n in op.output_names() if n]
            if not outs or not all(n.endswith("@GRAD") for n in outs):
                continue
            live = any(
                n in consumed or n in fetch_roots or (
                    (v := _var_of(block, n)) is not None
                    and (v.persistable or v.is_data))
                for n in outs)
            if not live:
                out.append(Diagnostic(
                    "V102", WARNING,
                    f"gradient var(s) {outs} dangle: produced but "
                    f"consumed by no optimizer/reduction op in a "
                    f"training program (a rewrite dropped the consumer)",
                    block_idx=0, op_idx=i, op_type=op.type,
                    op_uid=op.attrs.get("op_uid"), var=outs[0]))

    # V107 feed/fetch integrity
    for b in program.blocks:
        for v in b.vars.values():
            if v.is_data and v.persistable:
                out.append(Diagnostic(
                    "V107", ERROR,
                    f"var {v.name!r} is both feed data and persistable: "
                    f"it would be fed AND donated as jitted state in the "
                    f"same step", block_idx=b.idx, var=v.name))
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        for n in op.output_names():
            v = _var_of(block, n) if n else None
            if v is not None and v.is_data:
                out.append(Diagnostic(
                    "V107", ERROR,
                    f"op overwrites feed var {n!r}; the next step's feed "
                    f"would silently clobber (or be clobbered by) it",
                    block_idx=0, op_idx=i, op_type=op.type,
                    op_uid=op.attrs.get("op_uid"), var=n))
    for n in fetch_roots:
        if not block.has_var(n) and n not in available:
            out.append(Diagnostic(
                "V107", ERROR,
                f"fetch target {n!r} exists nowhere in the program",
                var=n))

    _check_shapes(program, out)


def _check_shapes(program: Program, out: List[Diagnostic]):
    """V103/V104: re-derive each op's output shape/dtype by the same
    abstract evaluation `core/infer_shape.py` uses at build time and
    compare against the DECLARED VarDescs.  Catches pass-emitted ops
    whose hand-declared temps disagree with the kernel (a dtype clash
    the trace would only surface as a deep XLA error, or a shape clash
    that silently broadcasts).  Mesh-dependent ops are skipped (their
    off-mesh degraded shapes differ by design), as are grad ops (their
    cotangent slot convention makes abstract evaluation ambiguous here —
    build-time infer_shape already covered them)."""
    import jax
    from ..core.infer_shape import _struct_for, _SENTINEL
    from ..ops.registry import get_op_info, OpContext

    block = program.global_block()
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch") or op.type in _MESH_DEPENDENT_OPS \
                or op.type in _CONTROL_FLOW_OPS \
                or op.type.endswith("_grad"):
            continue
        if op.attrs.get("zero_sharded") or any(
                (v := _var_of(block, n)) is not None
                and v.attrs.get("dp_shard")
                for n in op.input_names() + op.output_names() if n):
            # sharded bucket update: slot operands are declared at the
            # GLOBAL padded shape but each rank traces its 1/N slice
            # under shard_map — off-mesh abstract shapes differ by design
            continue
        info = get_op_info(op.type)
        if info is None:
            continue  # V109 already reported
        ins = {}
        complete = True
        for slot in info.inputs:
            names = op.inputs.get(slot.name, [])
            if not names:
                if not slot.optional:
                    complete = False
                    break
                ins[slot.name] = [] if slot.duplicable else None
                continue
            try:
                structs = [_struct_for(block.var(n)) for n in names if n]
            except (KeyError, NotImplementedError):
                complete = False
                break
            ins[slot.name] = structs if slot.duplicable else structs[0]
        if not complete:
            continue
        try:
            if info.infer_shape is not None:
                outs = info.infer_shape(ins, op.attrs)
            else:
                ctx = OpContext(seed=0)
                outs = jax.eval_shape(
                    lambda i_: info.kernel(i_, op.attrs, ctx), ins)
        except Exception:
            continue  # kernel refused the abstract operands; not a verdict
        if not isinstance(outs, dict):
            continue
        for slot in info.outputs:
            names = op.outputs.get(slot.name, [])
            res = outs.get(slot.name)
            if not names or res is None:
                continue
            res_list = res if isinstance(res, (list, tuple)) else [res]
            for name, st in zip(names, res_list):
                if not name or st is None or not hasattr(st, "shape"):
                    continue
                v = _var_of(block, name)
                if v is None:
                    continue
                inferred_shape = tuple(-1 if s == _SENTINEL else int(s)
                                       for s in st.shape)
                inferred_dtype = str(np.dtype(st.dtype).name) \
                    if hasattr(st, "dtype") else None
                if v.dtype is not None and inferred_dtype is not None \
                        and v.dtype != inferred_dtype:
                    out.append(Diagnostic(
                        "V103", ERROR,
                        f"declared dtype {v.dtype} of {name!r} clashes "
                        f"with the kernel's inferred {inferred_dtype}",
                        block_idx=0, op_idx=i, op_type=op.type,
                        op_uid=op.attrs.get("op_uid"), var=name))
                if v.shape is not None:
                    declared = tuple(int(s) for s in v.shape)
                    if len(declared) != len(inferred_shape) or any(
                            d >= 0 and s >= 0 and d != s
                            for d, s in zip(declared, inferred_shape)):
                        out.append(Diagnostic(
                            "V104", ERROR,
                            f"declared shape {list(declared)} of {name!r} "
                            f"clashes with the kernel's inferred "
                            f"{list(inferred_shape)}",
                            block_idx=0, op_idx=i, op_type=op.type,
                            op_uid=op.attrs.get("op_uid"), var=name))


# ---------------------------------------------------------------------------
# suite 2: SPMD / collective checker
# ---------------------------------------------------------------------------
def _check_collectives(program: Program, out: List[Diagnostic]):
    seq = collective_sequence(program)
    block = program.global_block()

    # V205: a collective inside a control-flow sub-block.  Under
    # shard_map every rank traces the same op list, but a sub-block runs
    # under lax.while_loop/cond whose predicate is DATA — per-rank data
    # diverges, so one rank can take an iteration (and post a collective)
    # its peers never reach: a guaranteed deadlock on a real mesh.
    for e in seq:
        if e["block"] != 0:
            out.append(Diagnostic(
                "V205", ERROR,
                f"collective {e['type']!r} inside control-flow sub-block "
                f"{e['block']}: a rank-divergent trip count deadlocks "
                f"the mesh (hoist the collective out of the loop/branch)",
                block_idx=e["block"], op_idx=e["index"],
                op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))

    # V202a: dp_degree consensus on each ring (the sharding pass stamps
    # the world it padded buckets for — two degrees on one ring means
    # two passes rewrote for different worlds)
    ring_degrees: Dict[int, Set[int]] = {}
    for e in seq:
        if e["dp_degree"] is not None:
            ring_degrees.setdefault(e["ring_id"], set()).add(e["dp_degree"])
    for ring, degs in ring_degrees.items():
        if len(degs) > 1:
            out.append(Diagnostic(
                "V202", ERROR,
                f"collectives on ring {ring} disagree on dp_degree "
                f"{sorted(degs)}: the program was rewritten for two "
                f"different worlds", var=None))

    # V203: per-op operand consistency for degree-stamped shard ops
    for e in seq:
        if e["type"] not in ("c_reducescatter", "c_allgather") or \
                e["dp_degree"] is None:
            continue
        d = e["dp_degree"]
        op = program.blocks[e["block"]].ops[e["index"]]
        in_v = _var_of(block, e["var"]) if e["var"] else None
        out_names = op.outputs.get("Out", [])
        out_v = _var_of(block, out_names[0]) if out_names else None
        in_n = _numel(in_v.shape) if in_v is not None else None
        out_n = _numel(out_v.shape) if out_v is not None else None
        if in_v is not None and out_v is not None and \
                in_v.dtype and out_v.dtype and in_v.dtype != out_v.dtype:
            out.append(Diagnostic(
                "V203", ERROR,
                f"{e['type']} input dtype {in_v.dtype} != output dtype "
                f"{out_v.dtype} (collectives preserve dtype; cast "
                f"separately)", block_idx=e["block"], op_idx=e["index"],
                op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))
        if e["type"] == "c_reducescatter" and in_n is not None:
            if in_n % d != 0:
                out.append(Diagnostic(
                    "V203", ERROR,
                    f"c_reducescatter input numel {in_n} is not divisible "
                    f"by dp_degree {d}: the shard split is ill-formed",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))
            elif out_n is not None and out_n != in_n // d:
                out.append(Diagnostic(
                    "V203", ERROR,
                    f"c_reducescatter output numel {out_n} != input "
                    f"{in_n} / dp_degree {d}",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))
        if e["type"] == "c_allgather" and in_n is not None and \
                out_n is not None:
            if e.get("x_dp_shard"):
                # ZeRO-3 JIT gather: the operand is DECLARED at the
                # global padded shape (each rank's traced slice is 1/d),
                # so the gathered output must equal the declared input
                if out_n != in_n:
                    out.append(Diagnostic(
                        "V203", ERROR,
                        f"c_allgather of dp_shard var: output numel "
                        f"{out_n} != the bucket's declared global numel "
                        f"{in_n}",
                        block_idx=e["block"], op_idx=e["index"],
                        op_type=e["type"], op_uid=e["op_uid"],
                        var=e["var"]))
            elif out_n != in_n * d:
                out.append(Diagnostic(
                    "V203", ERROR,
                    f"c_allgather output numel {out_n} != input {in_n} × "
                    f"dp_degree {d}",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))

    # V201/V202b: reduce-scatter ↔ allgather pairing with matching
    # bucket plans, validated AGAINST THE RECORDED STAGE.  The ZeRO-1/2
    # recipe is rs(bucket) → sharded update → ag(shard): every
    # degree-stamped rs must be followed by an ag whose local operand is
    # the same shard length, on the same ring.  ZeRO-3 changes both
    # halves: a JIT param gather (``zero_role`` gather_fwd/gather_bwd)
    # is not a publish — it must read a dp_shard param bucket — and the
    # grad reduce-scatter's "gathered counterpart" is the NEXT step's
    # forward gather, so instead of an ag pairing the rs must reach (via
    # pass-inserted plumbing — the gradient-merge shard accumulator
    # included) a ``zero_sharded`` update writing a dp_shard bucket in
    # place.  Pair the rest greedily in program order by shard numel;
    # ring mismatches on an otherwise-matching pair get the sharper
    # V202.
    block0 = program.global_block()
    consumers: Dict[str, List[OpDesc]] = {}
    for op in block0.ops:
        for n in op.input_names():
            if n:
                consumers.setdefault(n, []).append(op)

    def _reaches_inplace_sharded_update(rs_entry) -> bool:
        """rs output → (transparent plumbing)* → op with `zero_sharded`
        whose ParamOut is a dp_shard var (the ZeRO-3 in-place bucket
        update — the structural witness that the publish is deferred to
        the next step's gather)."""
        op0 = program.blocks[rs_entry["block"]].ops[rs_entry["index"]]
        frontier = [n for n in op0.outputs.get("Out", []) if n]
        seen: Set[str] = set()
        hops = 64
        while frontier and hops > 0:
            hops -= 1
            n = frontier.pop()
            if n in seen:
                continue
            seen.add(n)
            for c in consumers.get(n, ()):
                if c.attrs.get("zero_sharded"):
                    pouts = c.outputs.get("ParamOut", [])
                    pv = _var_of(block0, pouts[0]) if pouts else None
                    if pv is not None and pv.attrs.get("dp_shard"):
                        return True
                    # under gradient_merge the update's ParamOut is a
                    # @MASKED temp and the bucket write is the deferred
                    # where(mask, temp, bucket) commit — follow it
                    for w in consumers.get(pouts[0] if pouts else "", ()):
                        if w.type != "where":
                            continue
                        wouts = w.outputs.get("Out", [])
                        wv = _var_of(block0, wouts[0]) if wouts else None
                        if wv is not None and wv.attrs.get("dp_shard"):
                            return True
                    continue
                if c.type in _REDUCE_TRANSPARENT or \
                        c.type in ("elementwise_add", "scale", "where"):
                    frontier.extend(m for m in c.output_names() if m)
        return False

    rs_open: List[dict] = []
    for e in seq:
        if e["dp_degree"] is None:
            continue
        if e["type"] == "c_reducescatter":
            if e.get("zero_stage") == 3 and \
                    _reaches_inplace_sharded_update(e):
                # deferred publish: the sharded update writes the param
                # bucket in place; the next step's JIT gather is the ag
                continue
            d = e["dp_degree"]
            n = _numel(e["shape"])
            e["_shard"] = (n // d) if (n is not None and d and
                                       n % d == 0) else None
            rs_open.append(e)
        elif e["type"] == "c_allgather":
            if e.get("zero_role") in ("gather_fwd", "gather_bwd"):
                # ZeRO-3 JIT param gather: never part of the publish
                # pairing, but it must actually read sharded state — a
                # gather of a replicated var would move (g-1)× the full
                # params over ICI for nothing
                if not e.get("x_dp_shard"):
                    out.append(Diagnostic(
                        "V201", ERROR,
                        f"ZeRO-3 JIT param gather reads {e['var']!r}, "
                        f"which is not a dp_shard-marked bucket: the "
                        f"gather would replicate an already-replicated "
                        f"buffer (stage stamp disagrees with the "
                        f"program's sharded state)",
                        block_idx=e["block"], op_idx=e["index"],
                        op_type=e["type"], op_uid=e["op_uid"],
                        var=e["var"]))
                continue
            n = _numel(e["shape"])  # ag input IS the local shard
            match = next((r for r in rs_open if r["_shard"] is not None
                          and r["_shard"] == n), None)
            if match is None:
                out.append(Diagnostic(
                    "V201", ERROR,
                    f"c_allgather (shard numel {n}) has no preceding "
                    f"unpaired c_reducescatter with a matching bucket "
                    f"plan — swapped collective order or an orphaned "
                    f"publish (every rank would gather stale shards)",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))
            else:
                rs_open.remove(match)
                if match["ring_id"] != e["ring_id"]:
                    out.append(Diagnostic(
                        "V202", ERROR,
                        f"paired c_reducescatter (ring {match['ring_id']}) "
                        f"and c_allgather (ring {e['ring_id']}) ride "
                        f"different rings: the publish gathers over a "
                        f"different device group than the reduction",
                        block_idx=e["block"], op_idx=e["index"],
                        op_type=e["type"], op_uid=e["op_uid"],
                        var=e["var"]))
    for r in rs_open:
        if r.get("zero_stage") == 3:
            out.append(Diagnostic(
                "V201", ERROR,
                f"ZeRO-3 c_reducescatter (bucket {r['var']!r}) reaches "
                f"no in-place sharded update of a dp_shard param bucket "
                f"and no publish allgather: the reduced gradients go "
                f"nowhere (the deferred-publish contract is broken)",
                block_idx=r["block"], op_idx=r["index"], op_type=r["type"],
                op_uid=r["op_uid"], var=r["var"]))
            continue
        out.append(Diagnostic(
            "V201", ERROR,
            f"c_reducescatter (bucket {r['var']!r}) is never published "
            f"back by a matching c_allgather: params stay stale on "
            f"{max((r['dp_degree'] or 2) - 1, 1)} of "
            f"{r['dp_degree']} ranks",
            block_idx=r["block"], op_idx=r["index"], op_type=r["type"],
            op_uid=r["op_uid"], var=r["var"]))

    # V204: dp_shard metadata consistency — degree AND stage.  Every op
    # the sharding pass emitted is stamped with the stage it was emitted
    # for; the recorded plan is the authority, and a disagreement means
    # the program was rewritten twice for different stages (or a stamp
    # was hand-edited) — the stage-aware V201/V203 rules above would
    # then be validating against the wrong contract.
    plan = getattr(program, "_zero_shard_plan", None)
    plan_degree = int(plan.dp_degree) if plan is not None and \
        getattr(plan, "buckets", None) else None
    plan_stage = int(getattr(plan, "stage", 1)) if plan is not None and \
        getattr(plan, "buckets", None) else None
    if plan_stage is not None:
        stamped_stages = {int(op.attrs["zero_stage"])
                          for b in program.blocks for op in b.ops
                          if op.attrs.get("zero_stage") is not None}
        for s in sorted(stamped_stages - {plan_stage}):
            out.append(Diagnostic(
                "V204", ERROR,
                f"ops stamped zero_stage={s} disagree with the recorded "
                f"ShardingPlan stage={plan_stage}: the program carries "
                f"two different ZeRO rewrites (or a stamp was edited) — "
                f"stage-aware collective validation is unsound"))
        has_pbucket = any(v.attrs.get("zero_param_bucket")
                          for b in program.blocks for v in b.vars.values())
        if has_pbucket and plan_stage < 3:
            out.append(Diagnostic(
                "V204", ERROR,
                f"a ZeRO-3 param bucket var exists but the recorded plan "
                f"says stage={plan_stage}: parameters are sharded without "
                f"the stage-3 gather/update contract on record"))
    stamped = {d for degs in ring_degrees.values() for d in degs}
    for b in program.blocks:
        for v in b.vars.values():
            ds = v.attrs.get("dp_shard")
            if not ds:
                continue
            ds = int(ds)
            if v.shape and int(v.shape[0]) % ds != 0:
                out.append(Diagnostic(
                    "V204", ERROR,
                    f"dp_shard({ds}) var {v.name!r} has leading dim "
                    f"{v.shape[0]} not divisible by the shard degree",
                    block_idx=b.idx, var=v.name))
            if plan_degree is not None and ds != plan_degree:
                out.append(Diagnostic(
                    "V204", ERROR,
                    f"dp_shard({ds}) var {v.name!r} disagrees with the "
                    f"program's ShardingPlan dp_degree={plan_degree}",
                    block_idx=b.idx, var=v.name))
            elif plan_degree is None and stamped and ds not in stamped:
                out.append(Diagnostic(
                    "V204", ERROR,
                    f"dp_shard({ds}) var {v.name!r} disagrees with the "
                    f"collectives' stamped dp_degree {sorted(stamped)}",
                    block_idx=b.idx, var=v.name))

    # V206: psum-reassociation hazard inside a bitwise-order fold path.
    # The elastic fold exists BECAUSE psum's reduction order is
    # implementation-defined; any order-sensitive psum collective on the
    # fold's ring silently re-introduces the world-size dependence.
    el_meta = getattr(program, "_elastic_meta", None)
    if el_meta is not None:
        for e in seq:
            if e["type"] in _PSUM_ORDER_SENSITIVE and e["ring_id"] == 0:
                if el_meta.get("zero_stage1") and e.get("zero_role"):
                    # elastic × ZeRO-1: the bucket reduce-scatter IS the
                    # composition's documented reduction — it trades the
                    # bitwise cross-topology contract for allclose
                    # (distributed/elastic.py), so it is not a latent
                    # reassociation hazard
                    continue
                out.append(Diagnostic(
                    "V206", ERROR,
                    f"{e['type']} on ring 0 inside an elastic program: "
                    f"psum order is implementation-defined, breaking the "
                    f"fold's bitwise topology invariance (reduce through "
                    f"c_elastic_fold instead)",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))

    # V207: double reduction — a reduction collective whose operand's
    # producer chain (through pass-inserted plumbing only) already
    # contains a reduction.  The idempotency contract
    # insert_grad_allreduce/shard_optimizer_states maintain by hand.
    producers: Dict[str, OpDesc] = {}
    for op in block.ops:
        for n in op.output_names():
            if n:
                producers[n] = op
    for i, op in enumerate(block.ops):
        if op.type not in _REDUCE_OPS:
            continue
        if op.type == "c_elastic_fold" and op.attrs.get("pre_reduced"):
            # elastic × ZeRO-1 window accumulation: X IS the bucket's
            # reduce-scattered shard by design — the fold skips its
            # gather half and only continues the accumulator
            continue
        frontier = [n for n in op.inputs.get("X", []) if n]
        seen: Set[str] = set()
        hops = 64
        while frontier and hops > 0:
            hops -= 1
            n = frontier.pop()
            if n in seen:
                continue
            seen.add(n)
            p = producers.get(n)
            if p is None or p is op:
                continue
            if p.type in _REDUCE_OPS:
                out.append(Diagnostic(
                    "V207", ERROR,
                    f"{op.type} re-reduces {n!r}, already reduced by "
                    f"{p.type} upstream: gradients would be scaled/"
                    f"summed twice (a reduction pass was applied twice)",
                    block_idx=0, op_idx=i, op_type=op.type,
                    op_uid=op.attrs.get("op_uid"), var=n))
                break
            if p.type in _REDUCE_TRANSPARENT:
                frontier.extend(p.input_names())

    # V208: a per-micro-step collective the scanned-window hoist would
    # have removed.  A gradient-merge program's publish-role collectives
    # (the ZeRO allgather after the masked commit) run under the merge
    # MASK — K-1 of every K dispatches move those bytes to publish
    # values the mask then discards.  The commit-tail hoist
    # (distributed/scan_window.mark_scan_hoist + Executor.run_steps)
    # runs them once per window instead; warn when the program merged
    # gradients but nothing recorded the hoist.  Keyed off the gm mask
    # (gm_role stamps / _gm_meta) + the publish zero_role stamps, so a
    # hand-built masked commit without the stamps stays silent rather
    # than false-positive.
    from ..core.pass_framework import has_applied
    gm_meta = getattr(program, "_gm_meta", None) or {}
    if int(gm_meta.get("k", 0) or 0) > 1 and \
            not has_applied(program, "scan_hoist"):
        has_mask = any(op.attrs.get("gm_role") == "mask"
                       for op in block.ops)
        for e in seq:
            if e.get("zero_role") == "publish" and has_mask:
                out.append(Diagnostic(
                    "V208", WARNING,
                    f"{e['type']} publishes under a gradient-merge mask "
                    f"(K={gm_meta['k']}): {gm_meta['k'] - 1} of every "
                    f"{gm_meta['k']} dispatches move these bytes for a "
                    f"masked-out commit — the scanned-window hoist "
                    f"(scan_window.mark_scan_hoist / run_steps) "
                    f"publishes once per window",
                    block_idx=e["block"], op_idx=e["index"],
                    op_type=e["type"], op_uid=e["op_uid"], var=e["var"]))

    _check_pass_order(program, out)


def _check_pass_order(program: Program, out: List[Diagnostic]):
    """V501-V503: composition contracts between the rewrite passes, and
    V504: plan drift — the program's actually-applied passes disagree
    with the auto-parallel plan recorded on it.  Both read the
    applied-passes registry (core/pass_framework.py)."""
    from ..core.pass_framework import applied_passes
    entries = applied_passes(program)
    order = [e["pass"] for e in entries]
    if "elastic" in order and "gradient_merge" in order:
        out.append(Diagnostic(
            "V501", ERROR,
            "elastic and gradient_merge both applied: the elastic "
            "schedule IS a masked accumulation window — stacking a "
            "second counter double-masks the optimizer commit"))
    el_meta = getattr(program, "_elastic_meta", None) or {}
    if "elastic" in order and "zero1_sharding" in order:
        if order.index("zero1_sharding") > order.index("elastic"):
            out.append(Diagnostic(
                "V503", ERROR,
                "zero1_sharding applied AFTER elastic: the sharding "
                "pass would bucket the fold's @MASKED temps — "
                "elasticize must run on the already-sharded program"))
        elif not el_meta.get("zero_stage1"):
            out.append(Diagnostic(
                "V503", ERROR,
                "elastic and zero1_sharding both applied but the "
                "elastic rewrite was not ZeRO-aware (no sharded window "
                "accumulators): the ordered fold reduces into "
                "REPLICATED accumulators while ZeRO-1 updates 1/N "
                "shards — re-run elasticize on the sharded program"))
    if "gradient_merge" in order and "zero1_sharding" in order and \
            order.index("gradient_merge") < order.index("zero1_sharding"):
        out.append(Diagnostic(
            "V502", ERROR,
            "zero1_sharding applied AFTER gradient_merge: sharding must "
            "run first so the masked commit wraps the bucketed sharded "
            "update (the reverse buckets the @MASKED temps and "
            "reduce-scatters every micro-step's partial sums)"))

    # V504: plan drift.  `static.plan_program`/`apply_plan` record the
    # chosen knobs as an "auto_parallel_plan" registry entry; the
    # rewrites the plan names record their own entries when applied.
    # A program whose ACTUAL rewrite state (remat / dp_shard degree /
    # gradient_merge K / ring op presence / shard bucket size) disagrees
    # with the recorded plan was hand-edited after planning — its bench
    # records and docs would attribute the numbers to knobs that never
    # ran.
    plans = [e for e in entries if e.get("pass") == "auto_parallel_plan"]
    if plans:
        plan = plans[-1]  # latest plan is the authority

        def _drift(knob, planned, applied):
            out.append(Diagnostic(
                "V504", ERROR,
                f"plan drift: recorded auto-parallel plan says "
                f"{knob}={planned!r} but the program's applied passes "
                f"say {applied!r} — the program was modified after "
                f"planning (re-plan, or apply the recorded plan)"))

        remat_applied = "recompute" in order
        if "remat" in plan and bool(plan["remat"]) != remat_applied:
            _drift("remat", bool(plan["remat"]), remat_applied)
        zs = next((e for e in reversed(entries)
                   if e["pass"] == "zero1_sharding"), None)
        dp_applied = int(zs.get("dp_degree", 0)) if zs else 0
        if "dp_shard" in plan and int(plan["dp_shard"] or 0) != dp_applied:
            _drift("dp_shard", int(plan["dp_shard"] or 0), dp_applied)
        stage_applied = int(zs.get("stage", 1)) if zs else 0
        if "zero_stage" in plan and \
                int(plan["zero_stage"] or 0) != stage_applied:
            _drift("zero_stage", int(plan["zero_stage"] or 0),
                   stage_applied)
        if zs is not None and plan.get("bucket_mb") and \
                zs.get("bucket_bytes") and \
                int(plan["bucket_mb"]) * 2 ** 20 != int(zs["bucket_bytes"]):
            _drift("bucket_mb", int(plan["bucket_mb"]),
                   int(zs["bucket_bytes"]) // 2 ** 20)
        gm = next((e for e in reversed(entries)
                   if e["pass"] == "gradient_merge"), None)
        gm_applied = int(gm.get("k", 0)) if gm else 1
        if "grad_merge" in plan and \
                int(plan["grad_merge"] or 1) != gm_applied:
            _drift("grad_merge", int(plan["grad_merge"] or 1), gm_applied)
        if "ring" in plan:
            has_ring = any(op.type == "ring_attention"
                           for b in program.blocks for op in b.ops)
            if bool(plan["ring"]) != has_ring:
                _drift("ring", bool(plan["ring"]), has_ring)
        if "tp_degree" in plan:
            # the applied tp degree is a BUILD property (a plan claiming
            # tp on a plain build, or a tp build whose plan says 0, is
            # the same knobs-never-ran drift as the ring knob); the
            # detection rule is shared with the planner's pinning
            from ..core.pass_framework import built_tp_degree
            tp_applied = built_tp_degree(program)
            if int(plan["tp_degree"] or 0) != tp_applied:
                _drift("tp_degree", int(plan["tp_degree"] or 0),
                       tp_applied)
        if "scan_hoist" in plan:
            # the hoist is a dispatch knob recorded by mark_scan_hoist —
            # a plan that priced the publish at 1/K over a program
            # nobody marked (or a marked program whose plan priced the
            # looped wire) attributes bytes that never moved
            hoist_applied = "scan_hoist" in order
            if bool(plan["scan_hoist"]) != hoist_applied:
                _drift("scan_hoist", bool(plan["scan_hoist"]),
                       hoist_applied)


# ---------------------------------------------------------------------------
# suite 3: donation / alias analyzer
# ---------------------------------------------------------------------------
def _donated_names(program: Program) -> Set[str]:
    """Persistables the jitted step donates (donate_argnums=(0,) over the
    whole state dict): all of them — with the ZeRO shards and elastic/gm
    accumulators called out by the sharper checks."""
    return {v.name for b in program.blocks for v in b.vars.values()
            if v.persistable}


def _check_donation(program: Program, startup: Optional[Program],
                    fetch_roots: Set[str], out: List[Diagnostic]):
    block = program.global_block()

    # V301: alias-creating assigns between persistables in the STARTUP
    # (eager) program.  `assign` binds the same device buffer under two
    # scope names; the next jitted step donates the state dict, so XLA
    # receives one buffer twice — an execution error at best, silent
    # reuse at worst.  (The Lookahead optimizer routes this through
    # scale(1.0) for exactly this reason.)
    for prog in ([startup] if startup is not None else []):
        sb = prog.global_block()
        for i, op in enumerate(sb.ops):
            if op.type != "assign":
                continue
            src = (op.inputs.get("X") or [None])[0]
            dst = (op.outputs.get("Out") or [None])[0]
            sv = _var_of(sb, src) if src else None
            dv = _var_of(sb, dst) if dst else None
            # the MAIN program's var table decides donation: startup
            # often declares mirrors of main persistables
            mv_src = _var_of(block, src) if src else None
            mv_dst = _var_of(block, dst) if dst else None
            src_p = (sv is not None and sv.persistable) or \
                (mv_src is not None and mv_src.persistable)
            dst_p = (dv is not None and dv.persistable) or \
                (mv_dst is not None and mv_dst.persistable)
            if src_p and dst_p and src != dst:
                out.append(Diagnostic(
                    "V301", ERROR,
                    f"startup assigns persistable {src!r} into "
                    f"persistable {dst!r}: both scope names alias ONE "
                    f"device buffer, which the jitted step then donates "
                    f"twice (use scale(x, 1.0) to copy instead)",
                    block_idx=0, op_idx=i, op_type=op.type,
                    op_uid=op.attrs.get("op_uid"), var=dst))

    # V302: read-after-donation.  The optimizer commit is the donation
    # point of a persistable's old buffer: once an Optimize-role op has
    # written param/slot P, a LATER forward/backward-role op reading P
    # sees the UPDATED value — gradients computed against half-updated
    # state, the classic swapped-pass-order bug.  (Optimize-role readers
    # are the masked-commit machinery reading its own temps: fine.)
    donated_at: Dict[str, Tuple[int, OpDesc]] = {}
    donated = _donated_names(program)
    for i, op in enumerate(block.ops):
        if _is_fwd_bwd_read(op) and op.type not in ("feed", "fetch"):
            for n in op.input_names():
                hit = donated_at.get(n)
                if hit is not None:
                    j, wop = hit
                    out.append(Diagnostic(
                        "V302", ERROR,
                        f"{op.type!r} (role fwd/bwd) reads persistable "
                        f"{n!r} AFTER its optimizer commit by "
                        f"{wop.type!r} at op {j}: the old buffer is "
                        f"donated — this read sees the post-update "
                        f"value (pass ordering bug)",
                        block_idx=0, op_idx=i, op_type=op.type,
                        op_uid=op.attrs.get("op_uid"), var=n))
        if _is_optimize_write(op):
            for n in op.output_names():
                if n in donated:
                    donated_at.setdefault(n, (i, op))

    # V303: fetching a per-rank shard.  dp_shard persistables live
    # sharded over the mesh (CompiledProgram feeds them P("dp")); a
    # fetch replicates/aggregates, returning one rank's slice (or a
    # meaningless pmean of disjoint shards) — and snapshotting it
    # through a fetch races the donation.  Checkpoints read the GLOBAL
    # persistable through the scope instead.
    if fetch_roots:
        for b in program.blocks:
            for v in b.vars.values():
                if v.attrs.get("dp_shard") and v.name in fetch_roots:
                    out.append(Diagnostic(
                        "V303", ERROR,
                        f"fetch of ZeRO-sharded slot {v.name!r}: each "
                        f"rank holds 1/{v.attrs['dp_shard']} of it — a "
                        f"fetch returns garbage; snapshot it via "
                        f"Executor.checkpoint_snapshot instead",
                        block_idx=b.idx, var=v.name))


# ---------------------------------------------------------------------------
# suite 4: retrace lint
# ---------------------------------------------------------------------------
def _check_retrace(program: Program, out: List[Diagnostic]):
    block = program.global_block()
    for v in block.vars.values():
        if not v.is_data:
            continue
        if v.shape is None or len(v.shape) == 0:
            out.append(Diagnostic(
                "V403", WARNING,
                f"feed {v.name!r} is declared rank-0: with any scalar "
                f"feed in the signature the batch-dim bucketing policy "
                f"disables itself and every ragged batch retraces "
                f"(declare it shape [1] and reshape instead)",
                block_idx=0, var=v.name))
            continue
        dyn_tail = [i for i, d in enumerate(v.shape) if int(d) == -1
                    and i > 0]
        if dyn_tail:
            out.append(Diagnostic(
                "V401", WARNING,
                f"feed {v.name!r} shape {list(v.shape)} is dynamic in "
                f"dim(s) {dyn_tail}: FLAGS_feed_bucketing pads only the "
                f"leading batch dim, so every distinct length in those "
                f"dims compiles a fresh executable (pad/bucket them "
                f"host-side — io/bucketing.py)",
                block_idx=0, var=v.name))
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            for k, val in op.attrs.items():
                leaves = val if isinstance(val, (list, tuple)) else (val,)
                if any(isinstance(leaf, np.ndarray) or
                       type(leaf).__module__.startswith("jax")
                       for leaf in leaves):
                    out.append(Diagnostic(
                        "V402", WARNING,
                        f"op attr {k!r} holds a Python-captured array "
                        f"constant: it is baked "
                        f"into the trace and breaks fingerprint "
                        f"stability — a per-step value here retraces "
                        f"every step (feed it instead)",
                        block_idx=b.idx, op_idx=i, op_type=op.type,
                        op_uid=op.attrs.get("op_uid"), var=None))
                    break


# ---------------------------------------------------------------------------
# suite 5: sharding-propagation layout analyzer
# ---------------------------------------------------------------------------
def _check_layout(program: Program, out: List[Diagnostic]):
    """V601-V605 via the sharding-propagation analyzer
    (static/layout_analysis.py): infer every var's layout over the
    dp × mp mesh from the builders' annotations and flag kernel-contract
    conflicts, missing reductions, redundant reshards, mesh-axis
    disagreements and indivisible shards.  Model-axis findings only — a
    program with no tensor-parallel structure can't produce any."""
    from .layout_analysis import propagate_shardings
    out.extend(propagate_shardings(program).diagnostics)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def check_program(program: Program, level: str = "all",
                  startup: Optional[Program] = None,
                  fetch_list: Optional[Sequence] = None,
                  suppress: Iterable[str] = (),
                  raise_on_error: bool = False) -> VerifyReport:
    """Statically verify `program`'s op IR; returns a `VerifyReport`.

    ``level``: "graph" | "collective" | "donation" | "retrace" |
    "layout" | "all" (cumulative: "donation" runs
    graph+collective+donation), or an int 1-5.  ``startup``
    additionally checks init-time alias hazards (V301).  ``fetch_list``
    (vars or names) sharpens the dangling-var and shard-fetch checks.
    ``suppress`` drops diagnostic codes an allowlist has accepted.
    ``raise_on_error=True`` raises `ProgramVerificationError` when any
    error-severity diagnostic remains.

    Wired as ``paddle.static.check_program``; the same walk is run
    automatically at first compile and after every rewrite pass when
    ``PADDLE_TPU_VERIFY`` is set (docs/static_analysis.md).
    """
    if isinstance(level, int):
        depth = max(1, min(5, level))
    else:
        try:
            depth = _LEVELS[str(level)]
        except KeyError:
            raise ValueError(
                f"unknown verify level {level!r}: expected one of "
                f"{sorted(_LEVELS)} or an int 1-5")
    fetch_roots: Set[str] = set()
    for f in (fetch_list or []):
        fetch_roots.add(f.name if hasattr(f, "name") else str(f))
    fetch_roots.update(getattr(program, "_fetch_names", ()) or ())

    diags: List[Diagnostic] = []
    _check_graph(program, fetch_roots, diags)
    if depth >= 2:
        _check_collectives(program, diags)
    if depth >= 3:
        _check_donation(program, startup, fetch_roots, diags)
    if depth >= 4:
        _check_retrace(program, diags)
    if depth >= 5:
        _check_layout(program, diags)

    suppress = set(suppress)
    if suppress:
        diags = [d for d in diags if d.code not in suppress]
    from ..core.pass_framework import applied_passes
    report = VerifyReport(diags, level=str(level),
                          applied_passes=applied_passes(program))
    if raise_on_error:
        report.raise_on_error()
    return report


def verify_mode() -> str:
    """The PADDLE_TPU_VERIFY env contract: "" (off), "warn" (report
    defects as RuntimeWarnings), "strict" (raise on error diagnostics).
    Any other truthy value (e.g. "1") means "warn"."""
    raw = os.environ.get(VERIFY_ENV, "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return ""
    if raw == "strict":
        return "strict"
    return "warn"


def self_check(program: Program, pass_name: str,
               startup: Optional[Program] = None):
    """Post-rewrite self-verification hook for the rewrite passes
    (sharding, elastic, gradient_merge, recompute, AMP): a no-op unless
    PADDLE_TPU_VERIFY is set; in "strict" mode a pass that emitted
    broken IR raises at the rewrite site (with the pass named), in
    "warn" mode it warns and continues."""
    mode = verify_mode()
    if not mode:
        return None
    report = check_program(program, level="all", startup=startup)
    if report.errors and mode == "strict":
        raise ProgramVerificationError(report,
                                       context=f"after pass {pass_name!r}")
    if report.diagnostics:
        import warnings
        warnings.warn(
            f"PADDLE_TPU_VERIFY: pass {pass_name!r} left "
            f"{len(report.errors)} error(s) / {len(report.warnings)} "
            f"warning(s):\n{report.render()}", RuntimeWarning,
            stacklevel=3)
    return report


_verified_fingerprints: Set[Tuple] = set()


def verify_first_compile(program: Program,
                         fetch_list: Optional[Sequence] = None):
    """First-compile hook (Executor/_run_compiled, run_steps, and
    CompiledProgram on a trace-cache miss): verifies each distinct
    (program, fetch set) once per process when PADDLE_TPU_VERIFY is
    set.  Memoized by fingerprint + fetch names — the fetch set is part
    of what gets checked (V107 missing fetch, V303 shard fetch), so a
    later compile of the same program with new fetches re-verifies.
    The check costs an IR walk + abstract evaluation, so it rides the
    (already slow) compile path only."""
    mode = verify_mode()
    if not mode:
        return None
    fetch_key = tuple(sorted(
        f.name if hasattr(f, "name") else str(f)
        for f in (fetch_list or [])))
    try:
        fp = (program.fingerprint(), fetch_key)
    except Exception:
        fp = None
    if fp is not None and fp in _verified_fingerprints:
        return None
    report = check_program(program, level="all", fetch_list=fetch_list)
    if report.errors and mode == "strict":
        # memoize only CLEAN outcomes: a retried run of the same broken
        # program must hit the gate again, not the memo
        raise ProgramVerificationError(report, context="first compile")
    if fp is not None:
        _verified_fingerprints.add(fp)
    if report.diagnostics:
        import warnings
        warnings.warn(
            f"PADDLE_TPU_VERIFY (first compile): {len(report.errors)} "
            f"error(s) / {len(report.warnings)} warning(s):\n"
            f"{report.render()}", RuntimeWarning, stacklevel=3)
    return report

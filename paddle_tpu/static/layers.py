"""Static-graph layer functions: the fluid.layers API surface.

Analog of /root/reference/python/paddle/fluid/layers/nn.py (fc :168,
conv2d :1405, pool2d, batch_norm :2320, dropout, embedding :393, concat...),
layers/tensor.py (fill_constant, cast, assign...), layers/loss.py
(cross_entropy, softmax_with_cross_entropy :1253), layers/control_flow.py.

Each function creates parameters via LayerHelper (init ops into the startup
program) and appends one or more ops to the current main program block; the
TPU executor later traces the whole block into a single XLA computation.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.program import (VarDesc, default_main_program, unique_name,
                            OpRole)
from .layer_helper import LayerHelper
from .initializer import Constant, Xavier, Normal, NumpyArrayInitializer
from .param_attr import ParamAttr

__all__ = [
    "data", "fc", "embedding", "conv2d", "conv2d_transpose", "conv3d",
    "pool2d", "pool3d", "adaptive_pool2d", "batch_norm", "layer_norm",
    "group_norm", "instance_norm", "dropout", "softmax",
    "cross_entropy", "softmax_with_cross_entropy", "mean", "mul", "matmul",
    "concat", "split", "stack", "reshape", "squeeze", "unsqueeze", "flatten",
    "transpose", "cast", "scale", "sums", "sum", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "elementwise_mod",
    "elementwise_floordiv", "reduce_sum", "reduce_mean", "reduce_max",
    "reduce_min", "reduce_prod", "reduce_all", "reduce_any", "fill_constant",
    "fill_constant_batch_size_like", "assign", "zeros", "ones", "zeros_like",
    "ones_like", "uniform_random", "gaussian_random", "one_hot", "accuracy",
    "auc", "relu", "sigmoid", "tanh", "gelu", "sqrt", "square", "exp", "log",
    "abs", "pow", "clip", "clip_by_norm", "topk", "argmax", "argmin",
    "argsort", "gather", "gather_nd", "scatter", "slice", "expand", "tile",
    "lookup_table", "cos", "sin", "hard_swish", "relu6", "leaky_relu", "prelu",
    "swish", "softplus", "softsign", "log_softmax", "sigmoid_cross_entropy_with_logits",
    "smooth_l1", "huber_loss", "kldiv_loss", "mse_loss", "l2_normalize",
    "label_smooth", "pad", "pad2d", "shape", "increment", "equal", "not_equal",
    "less_than", "less_equal", "greater_than", "greater_equal", "logical_and",
    "logical_or", "logical_not", "where", "arange", "linspace", "create_tensor",
    "create_global_var", "unstack", "_binary_op", "sequence_mask", "cumsum",
    "maxout", "lrn", "resize_bilinear", "resize_nearest", "roi_align", "nce",
    "hsigmoid", "sampled_softmax_with_cross_entropy",
    "row_conv", "beam_search", "dynamic_lstmp", "chunk_eval",
    "deformable_conv", "density_prior_box",
]


def _current_block():
    return default_main_program().current_block()


def _to_var(x, block=None, dtype=None):
    """Coerce python scalars / numpy arrays to vars via fill_constant /
    assign_value."""
    if isinstance(x, VarDesc):
        return x
    block = block or _current_block()
    if np.isscalar(x):
        dtype = dtype or ("int64" if isinstance(x, (int, np.integer))
                          else "float32")
        return fill_constant([1], dtype, float(x))
    arr = np.asarray(x)
    out = block.create_var(shape=arr.shape, dtype=str(arr.dtype))
    block.append_op("assign_value", outputs={"Out": out},
                    attrs={"shape": list(arr.shape), "dtype": str(arr.dtype),
                           "values": arr.ravel().tolist()})
    return out


# ---------------------------------------------------------------------------
# data / feed
# ---------------------------------------------------------------------------
def data(name, shape, dtype="float32", lod_level=0, append_batch_size=False):
    """Declare an input var (fluid.data / fluid.layers.data). Dim -1 = batch,
    bound at first Executor.run."""
    block = default_main_program().global_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    v = block.create_var(name=name, shape=shape, dtype=dtype,
                         is_data=True, stop_gradient=True)
    v.lod_level = lod_level
    return v


# ---------------------------------------------------------------------------
# dense / conv / pool / norm
# ---------------------------------------------------------------------------
def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid.layers.fc (layers/nn.py:168): flatten trailing dims, x @ W + b."""
    helper = LayerHelper("fc", name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    outs = []
    for x in inputs:
        in_features = int(np.prod(x.shape[num_flatten_dims:]))
        w = helper.create_parameter(param_attr, [in_features, size], x.dtype)
        tmp = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op("mul", inputs={"X": x, "Y": w},
                         outputs={"Out": tmp},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        outs.append(tmp)
    if len(outs) > 1:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", inputs={"X": outs}, outputs={"Out": pre_bias})
    else:
        pre_bias = outs[0]
    b = helper.create_parameter(bias_attr, [size], inputs[0].dtype,
                                is_bias=True)
    if b is not None:
        pre_act = helper.create_variable_for_type_inference(pre_bias.dtype)
        helper.append_op("elementwise_add", inputs={"X": pre_bias, "Y": b},
                         outputs={"Out": pre_act},
                         attrs={"axis": len(pre_bias.shape) - 1})
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act, act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """fluid.layers.embedding / fluid.embedding (nn.py:393). is_sparse=True
    routes the gradient through the SelectedRows path
    (core/selected_rows.py): the backward emits {rows, values} and the
    optimizer scatter-adds into the table — the dense [vocab, width]
    gradient never materializes (reference selected_rows.h:41)."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, list(size), dtype,
                                default_initializer=Xavier())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lookup_table_v2", inputs={"W": w, "Ids": input},
        outputs={"Out": out},
        attrs={"padding_idx": -1 if padding_idx is None else padding_idx,
               "is_sparse": bool(is_sparse),
               "is_distributed": bool(is_distributed)})
    return out


lookup_table = embedding


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """fluid.layers.conv2d (nn.py:1405); lowers to
    lax.conv_general_dilated (MXU)."""
    helper = LayerHelper("conv2d", name=name)
    c_in = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    ks = _pair(filter_size)
    w_shape = [num_filters, c_in // groups] + ks
    fan_in = (c_in // groups) * ks[0] * ks[1]
    w = helper.create_parameter(
        param_attr, w_shape, input.dtype,
        default_initializer=Normal(0.0, float(np.sqrt(2.0 / fan_in))))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d", inputs={"Input": input, "Filter": w},
        outputs={"Output": out},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups,
               "data_format": data_format})
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        pre_act = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": out, "Y": b},
                         outputs={"Out": pre_act},
                         attrs={"axis": 1 if data_format == "NCHW" else 3})
    else:
        pre_act = out
    return helper.append_activation(pre_act, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", name=name)
    c_in = input.shape[1]
    ks = _triple(filter_size)
    w = helper.create_parameter(param_attr,
                                [num_filters, c_in // groups] + ks,
                                input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d", inputs={"Input": input, "Filter": w},
                     outputs={"Output": out},
                     attrs={"strides": _triple(stride),
                            "paddings": _triple(padding),
                            "dilations": _triple(dilation), "groups": groups})
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        pre = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": out, "Y": b},
                         outputs={"Out": pre}, attrs={"axis": 1})
        out = pre
    return helper.append_activation(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name)
    c_in = input.shape[1]
    if filter_size is None:
        # derive kernel size from the requested output size, as the
        # reference does (layers/nn.py conv2d_transpose)
        if output_size is None:
            raise ValueError(
                "conv2d_transpose: one of output_size / filter_size required")
        osize = _pair(output_size)
        strides_, pads_, dils_ = _pair(stride), _pair(padding), _pair(dilation)
        ks = []
        for i in range(2):
            in_i = input.shape[2 + i]
            k = ((osize[i] - (in_i - 1) * strides_[i] + 2 * pads_[i] - 1)
                 // dils_[i] + 1)
            ks.append(int(k))
        filter_size = ks
    ks = _pair(filter_size)
    w = helper.create_parameter(param_attr, [c_in, num_filters // groups] + ks,
                                input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs2 = {"strides": _pair(stride), "paddings": _pair(padding),
              "dilations": _pair(dilation), "groups": groups}
    if output_size is not None:
        attrs2["output_size"] = _pair(output_size)
    helper.append_op("conv2d_transpose",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": out}, attrs=attrs2)
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        pre = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": out, "Y": b},
                         outputs={"Out": pre}, attrs={"axis": 1})
        out = pre
    return helper.append_activation(out, act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [int(v)] * 2


def _triple(v):
    return list(v) if isinstance(v, (list, tuple)) else [int(v)] * 3


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    """layers/nn.py conv3d_transpose: NCDHW transpose conv; filter
    layout [Cin, Cout/groups, kd, kh, kw] like the reference."""
    helper = LayerHelper("conv3d_transpose", name=name)
    c_in = input.shape[1]
    if filter_size is None:
        # derive the kernel from the requested output size, like
        # conv2d_transpose (reference layers/nn.py)
        if output_size is None:
            raise ValueError(
                "conv3d_transpose: one of output_size / filter_size "
                "required")
        import builtins
        osize = _triple(output_size)
        strides_, pads_ = _triple(stride), _triple(padding)
        dils_ = _triple(dilation)
        ks = []
        for i in builtins.range(3):
            in_i = input.shape[2 + i]
            k = ((osize[i] - (in_i - 1) * strides_[i] + 2 * pads_[i] - 1)
                 // dils_[i] + 1)
            ks.append(int(k))
        filter_size = ks
    ks = _triple(filter_size)
    w = helper.create_parameter(
        param_attr, [c_in, num_filters // groups] + ks, input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs3 = {"strides": _triple(stride), "paddings": _triple(padding),
              "dilations": _triple(dilation), "groups": groups}
    if output_size is not None:
        attrs3["output_size"] = _triple(output_size)
    helper.append_op("conv3d_transpose",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": out}, attrs=attrs3)
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        pre = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", inputs={"X": out, "Y": b},
                         outputs={"Out": pre}, attrs={"axis": 1})
        out = pre
    return helper.append_activation(out, act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """layers/nn.py data_norm: normalization by accumulated batch
    statistics (persistable BatchSize/BatchSum/BatchSquareSum), the CTR
    models' input normalizer."""
    helper = LayerHelper("data_norm", name=name)
    ndim = len(input.shape)
    c = input.shape[-1] if (data_layout == "NHWC" or ndim <= 2) \
        else input.shape[1]
    from .initializer import Constant
    stats = {}
    for key, init in (("BatchSize", 1e4), ("BatchSum", 0.0),
                      ("BatchSquareSum", 1e4)):
        v = helper.main_program.global_block().create_var(
            name=unique_name(f"{helper.name}_{key}"), shape=(c,),
            dtype=input.dtype, persistable=True, stop_gradient=True)
        Constant(init)(v, helper.startup_program.global_block())
        stats[key] = v
    y = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    dn_inputs = {"X": input, "BatchSize": stats["BatchSize"],
                 "BatchSum": stats["BatchSum"],
                 "BatchSquareSum": stats["BatchSquareSum"]}
    if enable_scale_and_shift:
        from .initializer import Constant as _Const
        dn_inputs["scale_w"] = helper.create_parameter(
            param_attr, [c], input.dtype,
            default_initializer=_Const(1.0))
        dn_inputs["bias"] = helper.create_parameter(
            param_attr, [c], input.dtype, is_bias=True,
            default_initializer=_Const(0.0))
    helper.append_op("data_norm", inputs=dn_inputs,
                     outputs={"Y": y, "Means": means, "Scales": scales},
                     attrs={"epsilon": epsilon,
                            "data_layout": data_layout})
    return helper.append_activation(y, act)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """layers/detection.py multi_box_head — the SSD prediction head:
    per feature map, conv loc/conf predictions + prior boxes; outputs
    (mbox_locs, mbox_confs, boxes, variances) concatenated across maps."""
    n_maps = len(inputs)
    if min_sizes is None:
        # reference ratio schedule: evenly spaced between min/max_ratio
        assert min_ratio is not None and max_ratio is not None
        min_sizes, max_sizes = [], []
        import builtins
        ratio_step = int((max_ratio - min_ratio) / builtins.max(n_maps - 2, 1))
        for ratio in builtins.range(min_ratio, max_ratio + 1, ratio_step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + ratio_step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes
    helper = LayerHelper("multi_box_head", name=name)
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        ms = min_sizes[i]
        mxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        ar = list(ar) if isinstance(ar, (list, tuple)) else [ar]
        boxes = helper.create_variable_for_type_inference("float32")
        variances = helper.create_variable_for_type_inference("float32")
        attrs = {"min_sizes": [float(ms)],
                 "aspect_ratios": [float(a) for a in ar],
                 "variances": list(variance), "flip": flip, "clip": clip,
                 "offset": offset}
        if mxs:
            attrs["max_sizes"] = [float(mxs)]
        if steps:
            attrs["step_w"] = float(steps[i])
            attrs["step_h"] = float(steps[i])
        if step_w:
            attrs["step_w"] = float(step_w[i]
                                    if isinstance(step_w, (list, tuple))
                                    else step_w)
        if step_h:
            attrs["step_h"] = float(step_h[i]
                                    if isinstance(step_h, (list, tuple))
                                    else step_h)
        if min_max_aspect_ratios_order:
            attrs["min_max_aspect_ratios_order"] = True
        helper.append_op("prior_box", inputs={"Input": x, "Image": image},
                         outputs={"Boxes": boxes, "Variances": variances},
                         attrs=attrs)
        # priors per cell come from the SAME expansion the kernel uses
        from ..ops.kernels.vision import expand_aspect_ratios
        num_priors = len(expand_aspect_ratios(ar, flip)) \
            + (1 if mxs else 0)
        loc = conv2d(x, num_priors * 4, kernel_size, stride=stride,
                     padding=pad)
        conf = conv2d(x, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad)
        # [N, P*4, H, W] -> [N, H*W*P, 4]
        loc = transpose(loc, [0, 2, 3, 1])
        loc = reshape(loc, [0, -1, 4])
        conf = transpose(conf, [0, 2, 3, 1])
        conf = reshape(conf, [0, -1, num_classes])
        boxes = reshape(boxes, [-1, 4])
        variances = reshape(variances, [-1, 4])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(boxes)
        vars_all.append(variances)
    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    box = concat(boxes_all, axis=0)
    var = concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, box, var


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None, data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d", inputs={"X": input}, outputs={"Out": out},
        attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
               "strides": _pair(pool_stride), "paddings": _pair(pool_padding),
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive, "data_format": data_format})
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d", inputs={"X": input}, outputs={"Out": out},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "strides": _triple(pool_stride),
               "paddings": _triple(pool_padding),
               "global_pooling": global_pooling})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pool2d", inputs={"X": input}, outputs={"Out": out},
                     attrs={"pooling_type": pool_type,
                            "ksize": _pair(pool_size), "adaptive": True})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False, name=None):
    """fluid.layers.batch_norm (nn.py:2320). Running stats are persistable
    non-trainable params updated in-graph (MeanOut/VarianceOut rebind)."""
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(param_attr, [c], "float32",
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(bias_attr, [c], "float32", is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False), [c], "float32",
        default_initializer=Constant(0.0))
    var = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False), [c], "float32",
        default_initializer=Constant(1.0))
    mean.stop_gradient = True
    var.stop_gradient = True
    y = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference("float32", True)
    saved_var = helper.create_variable_for_type_inference("float32", True)
    helper.append_op(
        "batch_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
                "Variance": var},
        outputs={"Y": y, "MeanOut": mean, "VarianceOut": var,
                 "SavedMean": saved_mean, "SavedVariance": saved_var},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "data_layout": data_layout, "is_test": is_test,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, "float32",
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, "float32",
                                    is_bias=True)
        if b is not None:
            inputs["Bias"] = b
    y = helper.create_variable_for_type_inference(input.dtype)
    m = helper.create_variable_for_type_inference("float32", True)
    v = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": y, "Mean": m, "Variance": v},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    inputs = {"X": input}
    s = helper.create_parameter(param_attr, [c], "float32",
                                default_initializer=Constant(1.0))
    inputs["Scale"] = s
    b = helper.create_parameter(bias_attr, [c], "float32", is_bias=True)
    if b is not None:
        inputs["Bias"] = b
    y = helper.create_variable_for_type_inference(input.dtype)
    m = helper.create_variable_for_type_inference("float32", True)
    v = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("group_norm", inputs=inputs,
                     outputs={"Y": y, "Mean": m, "Variance": v},
                     attrs={"groups": groups, "epsilon": epsilon,
                            "data_layout": data_layout})
    return helper.append_activation(y, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", name=name)
    c = input.shape[1]
    inputs = {"X": input}
    s = helper.create_parameter(param_attr, [c], "float32",
                                default_initializer=Constant(1.0))
    inputs["Scale"] = s
    b = helper.create_parameter(bias_attr, [c], "float32", is_bias=True)
    if b is not None:
        inputs["Bias"] = b
    y = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference("float32", True)
    sv = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("instance_norm", inputs=inputs,
                     outputs={"Y": y, "SavedMean": sm, "SavedVariance": sv},
                     attrs={"epsilon": epsilon})
    return y


def dropout(x, dropout_prob, is_test=False, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference("uint8", True)
    helper.append_op(
        "dropout", inputs={"X": x}, outputs={"Out": out, "Mask": mask},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "seed": seed or 0, "fix_seed": seed is not None,
               "dropout_implementation": dropout_implementation})
    return out


def softmax(input, axis=-1, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("softmax", inputs={"X": input}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("log_softmax", inputs={"X": input}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    helper = LayerHelper("cross_entropy", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy",
                     inputs={"X": input, "Label": label},
                     outputs={"Y": out},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False,
                               axis=-1, name=None):
    helper = LayerHelper("softmax_with_cross_entropy", name=name)
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": logits, "Label": label},
                     outputs={"Softmax": softmax_out, "Loss": loss},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis})
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": x, "Label": label}, outputs={"Out": out},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0,
              name=None):
    helper = LayerHelper("smooth_l1_loss", name=name)
    diff = helper.create_variable_for_type_inference(x.dtype, True)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x, "Y": y}
    if inside_weight is not None:
        ins["InsideWeight"] = inside_weight
    if outside_weight is not None:
        ins["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", inputs=ins,
                     outputs={"Diff": diff, "Out": out},
                     attrs={"sigma": sigma})
    return out


def huber_loss(input, label, delta, name=None):
    helper = LayerHelper("huber_loss", name=name)
    residual = helper.create_variable_for_type_inference(input.dtype, True)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("huber_loss", inputs={"X": input, "Y": label},
                     outputs={"Residual": residual, "Out": out},
                     attrs={"delta": delta})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("kldiv_loss", inputs={"X": x, "Target": target},
                     outputs={"Loss": out}, attrs={"reduction": reduction})
    return out


def mse_loss(input, label, name=None):
    sq = square(elementwise_sub(input, label))
    return reduce_mean(sq)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(label.dtype)
    ins = {"X": label}
    if prior_dist is not None:
        ins["PriorDist"] = prior_dist
    helper.append_op("label_smooth", inputs=ins, outputs={"Out": out},
                     attrs={"epsilon": epsilon})
    return out


# ---------------------------------------------------------------------------
# math / elementwise / reduce
# ---------------------------------------------------------------------------
def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", inputs={"X": x}, outputs={"Out": out})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mul", inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("matmul", inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def _binary_op(op_type, x, y, reverse=False, axis=-1):
    """Shared builder for VarDesc operator overloads and the elementwise_*
    functions."""
    block = _current_block()
    if not isinstance(x, VarDesc):
        x = _to_var(x, block, dtype=getattr(y, "dtype", None))
    if not isinstance(y, VarDesc):
        y = _to_var(y, block, dtype=x.dtype)
    if reverse:
        x, y = y, x
    helper = LayerHelper(op_type)
    cmp_ops = {"less_than", "less_equal", "greater_than", "greater_equal",
               "equal", "not_equal"}
    dtype = "bool" if op_type in cmp_ops else x.dtype
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(op_type, inputs={"X": x, "Y": y}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def _make_elementwise(op_type):
    def f(x, y, axis=-1, act=None, name=None):
        out = _binary_op(op_type, x, y, axis=axis)
        if act:
            helper = LayerHelper(op_type)
            out = helper.append_activation(out, act)
        return out
    f.__name__ = op_type
    return f


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")
elementwise_mod = _make_elementwise("elementwise_mod")
elementwise_floordiv = _make_elementwise("elementwise_floordiv")

def _make_compare(op_type, has_force_cpu=False):
    """Comparison layer; `cond=` writes the result into an existing bool
    var (fluid layers/control_flow.py less_than(..., cond=) parity — the
    idiom While bodies use to update their loop condition).  Only
    less_than takes force_cpu positionally, matching the reference
    signature less_than(x, y, force_cpu=None, cond=None)."""

    def _build(x, y, cond, axis, name):
        if cond is None:
            return _binary_op(op_type, x, y, axis=axis)
        helper = LayerHelper(op_type, name=name)
        helper.append_op(op_type, inputs={"X": x, "Y": y},
                         outputs={"Out": cond}, attrs={"axis": axis})
        return cond

    if has_force_cpu:
        def f(x, y, force_cpu=None, cond=None, axis=-1, name=None):
            return _build(x, y, cond, axis, name)
    else:
        def f(x, y, cond=None, axis=-1, name=None):
            return _build(x, y, cond, axis, name)
    f.__name__ = op_type
    return f


equal = _make_compare("equal")
not_equal = _make_compare("not_equal")
less_than = _make_compare("less_than", has_force_cpu=True)
less_equal = _make_compare("less_equal")
greater_than = _make_compare("greater_than")
greater_equal = _make_compare("greater_equal")
logical_and = _make_elementwise("logical_and")
logical_or = _make_elementwise("logical_or")


def logical_not(x, name=None):
    helper = LayerHelper("logical_not", name=name)
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("logical_not", inputs={"X": x}, outputs={"Out": out})
    return out


def _make_reduce(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype)
        if dim is None:
            attrs = {"reduce_all": True, "dim": [0], "keep_dim": keep_dim}
        else:
            d = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"reduce_all": False, "dim": list(d), "keep_dim": keep_dim}
        helper.append_op(op_type, inputs={"X": input}, outputs={"Out": out},
                         attrs=attrs)
        return out
    f.__name__ = op_type
    return f


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")
reduce_all = _make_reduce("reduce_all")
reduce_any = _make_reduce("reduce_any")


def _make_unary(op_type, out_dtype=None):
    def f(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
        helper.append_op(op_type, inputs={"X": x}, outputs={"Out": out})
        return out
    f.__name__ = op_type
    return f


relu = _make_unary("relu")
sigmoid = _make_unary("sigmoid")
tanh = _make_unary("tanh")
sqrt = _make_unary("sqrt")
square = _make_unary("square")
exp = _make_unary("exp")
log = _make_unary("log")
abs = _make_unary("abs")
cos = _make_unary("cos")
sin = _make_unary("sin")
relu6 = _make_unary("relu6")
softplus = _make_unary("softplus")
softsign = _make_unary("softsign")
swish = _make_unary("swish")
hard_swish = _make_unary("hard_swish")


def gelu(x, approximate=False, name=None):
    helper = LayerHelper("gelu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("gelu", inputs={"X": x}, outputs={"Out": out},
                     attrs={"approximate": approximate})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("leaky_relu", inputs={"X": x}, outputs={"Out": out},
                     attrs={"alpha": alpha})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    else:
        alpha_shape = [1] + list(x.shape[1:])
    alpha = helper.create_parameter(param_attr, alpha_shape, x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": x, "Alpha": alpha},
                     outputs={"Out": out}, attrs={"mode": mode})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pow", inputs={"X": x}, outputs={"Out": out},
                     attrs={"factor": factor})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("scale", inputs={"X": x}, outputs={"Out": out},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip", inputs={"X": x}, outputs={"Out": out},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": x}, outputs={"Out": out},
                     attrs={"max_norm": max_norm})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("norm", inputs={"X": x},
                     outputs={"Out": out, "Norm": norm},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def cumsum(x, axis=None, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("cumsum", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": -1 if axis is None else axis,
                            "flatten": axis is None})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": input}, outputs={"Out": out})
    return out


def sum(x, dim=None, keep_dim=False, name=None):
    if isinstance(x, (list, tuple)):
        return sums(x)
    return reduce_sum(x, dim, keep_dim, name)


# ---------------------------------------------------------------------------
# tensor manipulation
# ---------------------------------------------------------------------------
def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": list(input)},
                     outputs={"Out": out}, attrs={"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = []
    else:
        n = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op("split", inputs={"X": input}, outputs={"Out": outs},
                     attrs={"num": 0 if sections else n,
                            "sections": sections, "axis": dim})
    return outs


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": list(x)}, outputs={"Y": out},
                     attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(num)]
    helper.append_op("unstack", inputs={"X": x}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("reshape2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out, act)


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("squeeze2", inputs={"X": input},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("unsqueeze2", inputs={"X": input},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axes": list(axes)})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("flatten2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axis": axis})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, True)
    helper.append_op("transpose2", inputs={"X": x},
                     outputs={"Out": out, "XShape": xshape},
                     attrs={"axis": list(perm)})
    return out


def cast(x, dtype):
    from ..core.dtype import convert_dtype
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("cast", inputs={"X": x}, outputs={"Out": out},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def gather(input, index, axis=None, name=None):
    helper = LayerHelper("gather", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": input, "Index": index},
                     outputs={"Out": out},
                     attrs={"axis": 0 if axis is None else axis})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather_nd", inputs={"X": input, "Index": index},
                     outputs={"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("scatter",
                     inputs={"X": input, "Ids": index, "Updates": updates},
                     outputs={"Out": out}, attrs={"overwrite": overwrite})
    return out


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("slice", inputs={"Input": input}, outputs={"Out": out},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("expand", inputs={"X": x}, outputs={"Out": out},
                     attrs={"expand_times": list(expand_times)})
    return out


def tile(x, repeat_times, name=None):
    helper = LayerHelper("tile", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("tile", inputs={"X": x}, outputs={"Out": out},
                     attrs={"repeat_times": list(repeat_times)})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pad", inputs={"X": x}, outputs={"Out": out},
                     attrs={"paddings": list(paddings),
                            "pad_value": pad_value})
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("pad2d", inputs={"X": input}, outputs={"Out": out},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": pad_value,
                            "data_format": data_format})
    return out


def where(condition, x=None, y=None, name=None):
    helper = LayerHelper("where", name=name)
    if x is None and y is None:
        out = helper.create_variable_for_type_inference("int64", True)
        helper.append_op("where_index", inputs={"Condition": condition},
                         outputs={"Out": out})
        return out
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("where",
                     inputs={"Condition": condition, "X": x, "Y": y},
                     outputs={"Out": out})
    return out


def one_hot(input, depth, allow_out_of_range=False, name=None):
    helper = LayerHelper("one_hot_v2", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot_v2", inputs={"X": input}, outputs={"Out": out},
                     attrs={"depth": depth,
                            "allow_out_of_range": allow_out_of_range})
    return out


def shape(input, name=None):
    helper = LayerHelper("shape", name=name)
    out = helper.create_variable_for_type_inference("int32", True)
    helper.append_op("shape", inputs={"Input": input}, outputs={"Out": out})
    return out


def increment(x, value=1.0, in_place=True, name=None):
    helper = LayerHelper("increment", name=name)
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": x}, outputs={"Out": out},
                     attrs={"step": value})
    return out


# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------
def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    from ..core.dtype import convert_dtype
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("fill_constant", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    helper = LayerHelper("fill_constant_batch_size_like", name=name)
    from ..core.dtype import convert_dtype
    out = helper.create_variable_for_type_inference(convert_dtype(dtype), True)
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape),
                            "dtype": convert_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, VarDesc):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": input},
                         outputs={"Out": output})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(str(arr.dtype))
        helper.append_op("assign_value", outputs={"Out": output},
                         attrs={"shape": list(arr.shape),
                                "dtype": str(arr.dtype),
                                "values": arr.ravel().tolist()})
    return output


def zeros(shape, dtype="float32", force_cpu=False, name=None):
    return fill_constant(shape, dtype, 0.0, name=name)


def ones(shape, dtype="float32", force_cpu=False, name=None):
    return fill_constant(shape, dtype, 1.0, name=name)


def zeros_like(x, out=None, name=None):
    helper = LayerHelper("fill_zeros_like", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": x},
                     outputs={"Out": out})
    return out


def ones_like(x, out=None, name=None):
    helper = LayerHelper("fill_any_like", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_any_like", inputs={"X": x}, outputs={"Out": out},
                     attrs={"value": 1.0, "dtype": x.dtype})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    helper = LayerHelper("uniform_random", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("uniform_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    helper = LayerHelper("gaussian_random", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("gaussian_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    return out


def arange(start, end=None, step=1, dtype="int64", name=None):
    if end is None:
        start, end = 0, start
    helper = LayerHelper("range", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("range", outputs={"Out": out},
                     attrs={"start": start, "end": end, "step": step,
                            "dtype": dtype})
    return out


def linspace(start, stop, num, dtype="float32", name=None):
    helper = LayerHelper("linspace", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("linspace", outputs={"Out": out},
                     attrs={"start": start, "stop": stop, "num": num,
                            "dtype": dtype})
    return out


def create_tensor(dtype, name=None, persistable=False):
    block = _current_block()
    return block.create_var(name=name or unique_name("create_tensor"),
                            dtype=dtype, persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    v = helper.create_global_variable(shape, dtype, persistable=persistable,
                                      name=name)
    from .initializer import Constant as _C
    _C(value)(v, helper.startup_program.global_block())
    return v


# ---------------------------------------------------------------------------
# search / sort / metrics
# ---------------------------------------------------------------------------
def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("top_k", inputs={"X": input},
                     outputs={"Out": values, "Indices": indices},
                     attrs={"k": k})
    return values, indices


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_max", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("arg_min", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("argsort", inputs={"X": input},
                     outputs={"Out": out, "Indices": ids},
                     attrs={"axis": axis, "descending": descending})
    return out, ids


def square_error_cost(input, label):
    """layers/loss.py square_error_cost: (input - label)^2 elementwise."""
    return square(elementwise_sub(input, label))


def mean_iou(input, label, num_classes):
    """layers/nn.py mean_iou: mean intersection-over-union over classes;
    returns (mean_iou, out_wrong, out_correct)."""
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    miou.shape = (1,)
    wrong.shape = (num_classes,)
    correct.shape = (num_classes,)
    helper.append_op("mean_iou",
                     inputs={"Predictions": input, "Labels": label},
                     outputs={"OutMeanIou": miou, "OutWrong": wrong,
                              "OutCorrect": correct},
                     attrs={"num_classes": num_classes})
    return miou, wrong, correct


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """layers/control_flow.py Print: runtime tensor print that survives
    jit (lowers to the print op / jax.debug.print)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = tuple(input.shape) if input.shape is not None else None
    helper.append_op("print", inputs={"In": input},
                     outputs={"Out": out},
                     attrs={"message": message or "",
                            "first_n": first_n, "summarize": summarize})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """layers/metric_op.py accuracy: top-k accuracy of predictions."""
    helper = LayerHelper("accuracy")
    topk_out, topk_ids = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32", True)
    correct = correct or helper.create_variable_for_type_inference(
        "int32", True)
    total = total or helper.create_variable_for_type_inference("int32", True)
    helper.append_op("accuracy",
                     inputs={"Out": topk_out, "Indices": topk_ids,
                             "Label": label},
                     outputs={"Accuracy": acc, "Correct": correct,
                              "Total": total})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    helper = LayerHelper("auc")
    auc_out = helper.create_variable_for_type_inference("float64", True)
    stat_pos = helper.create_global_variable(
        [1, num_thresholds + 1], "int64", persistable=True)
    stat_neg = helper.create_global_variable(
        [1, num_thresholds + 1], "int64", persistable=True)
    from .initializer import Constant as _C
    _C(0.0)(stat_pos, helper.startup_program.global_block())
    _C(0.0)(stat_neg, helper.startup_program.global_block())
    pos_out = helper.create_variable_for_type_inference("int64", True)
    neg_out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("auc",
                     inputs={"Predict": input, "Label": label,
                             "StatPos": stat_pos, "StatNeg": stat_neg},
                     outputs={"AUC": auc_out, "StatPosOut": stat_pos,
                              "StatNegOut": stat_neg},
                     attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out, [stat_pos, stat_neg], [pos_out, neg_out]


# ---------------------------------------------------------------------------
# misc layers used by models
# ---------------------------------------------------------------------------
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype, True)
    helper.append_op("sequence_mask", inputs={"X": x}, outputs={"Y": out},
                     attrs={"maxlen": -1 if maxlen is None else maxlen,
                            "out_dtype": dtype})
    return out


def maxout(x, groups, name=None, axis=1):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("maxout", inputs={"X": x}, outputs={"Out": out},
                     attrs={"groups": groups, "axis": axis})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype, True)
    helper.append_op("lrn", inputs={"X": input},
                     outputs={"Out": out, "MidOut": mid},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def resize_bilinear(input, out_shape=None, scale=None, align_corners=True,
                    align_mode=1, data_format="NCHW", name=None):
    helper = LayerHelper("bilinear_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"interp_method": "bilinear", "align_corners": align_corners,
             "align_mode": align_mode, "data_layout": data_format}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("bilinear_interp", inputs={"X": input},
                     outputs={"Out": out}, attrs=attrs)
    return out


def resize_nearest(input, out_shape=None, scale=None, align_corners=True,
                   data_format="NCHW", name=None):
    helper = LayerHelper("nearest_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"interp_method": "nearest", "align_corners": align_corners,
             "data_layout": data_format}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("nearest_interp", inputs={"X": input},
                     outputs={"Out": out}, attrs=attrs)
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("roi_align", inputs={"X": input, "ROIs": rois},
                     outputs={"Out": out},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale,
                            "sampling_ratio": sampling_ratio})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv")
    filter_shape = [future_context_size + 1, input.shape[-1]]
    w = helper.create_parameter(param_attr, filter_shape, input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs={"X": input, "Filter": w},
                     outputs={"Out": out})
    return helper.append_activation(out, act)


_NCE_SAMPLERS = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """fluid.layers.nce (nce_op.cc:316): noise-contrastive estimation loss
    over sampled negatives.  is_sparse is accepted for parity (gradients
    here are dense gathers — XLA scatters are already sparse-shaped)."""
    helper = LayerHelper("nce", name=name)
    dim = input.shape[-1]
    num_neg_samples = int(num_neg_samples or 10)
    if sampler not in _NCE_SAMPLERS:
        raise ValueError(f"nce sampler must be one of {set(_NCE_SAMPLERS)}")
    w = helper.create_parameter(param_attr, [num_total_classes, dim],
                                input.dtype)
    inputs = {"Input": input, "Label": label, "Weight": w}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_total_classes, 1],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = b
    if sample_weight is not None:
        inputs["SampleWeight"] = sample_weight
    if sampler == "custom_dist":
        if custom_dist is None:
            raise ValueError("nce(sampler='custom_dist') needs custom_dist")
        import numpy as _np
        probs_var = assign(_np.asarray(custom_dist, _np.float32))
        inputs["CustomDistProbs"] = probs_var
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits_v = helper.create_variable_for_type_inference(input.dtype)
    sample_labels_v = helper.create_variable_for_type_inference("int64",
                                                                True)
    helper.append_op(
        "nce", inputs=inputs,
        outputs={"Cost": cost, "SampleLogits": sample_logits_v,
                 "SampleLabels": sample_labels_v},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples,
               "sampler": _NCE_SAMPLERS[sampler], "seed": seed,
               "is_sparse": is_sparse})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """fluid.layers.hsigmoid (hierarchical_sigmoid_op.cc:60): logistic
    loss over the label's root-to-leaf path of a complete binary tree
    (or a custom PathTable/PathCode tree)."""
    helper = LayerHelper("hsigmoid", name=name)
    dim = input.shape[-1]
    if is_custom and (path_table is None or path_code is None):
        raise ValueError("hsigmoid(is_custom=True) needs path_table and "
                         "path_code")
    if not is_custom and (num_classes is None or num_classes < 2):
        raise ValueError("hsigmoid needs num_classes >= 2")
    # custom trees index rows by the table's node ids; default trees use
    # the num_classes-1 internal nodes
    rows = num_classes if is_custom else num_classes - 1
    w = helper.create_parameter(param_attr, [rows, dim], input.dtype)
    inputs = {"X": input, "W": w, "Label": label}
    if is_custom:
        inputs["PathTable"] = path_table
        inputs["PathCode"] = path_code
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [rows, 1], input.dtype,
                                    is_bias=True)
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": out, "PreOut": pre_out},
        attrs={"num_classes": num_classes, "is_sparse": is_sparse})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """fluid.layers.sampled_softmax_with_cross_entropy
    (sample_logits_op.cc): softmax CE over the true classes plus
    num_samples log-uniform negatives, with log-Q correction."""
    label_width = (label.shape[-1] if label.shape is not None
                   and len(label.shape) > 1 else 1)
    if label_width != num_true:
        raise ValueError(
            f"num_true={num_true} does not match the label width "
            f"{label_width} — the label's last dim IS the true-class "
            "count")
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    samples = helper.create_variable_for_type_inference("int64", True)
    probabilities = helper.create_variable_for_type_inference(logits.dtype,
                                                              True)
    sampled_logits_v = helper.create_variable_for_type_inference(
        logits.dtype)
    sampled_labels_v = helper.create_variable_for_type_inference("int64",
                                                                 True)
    inputs = {"Logits": logits, "Labels": label}
    if use_customized_samples:
        if customized_samples is None or customized_probabilities is None:
            raise ValueError(
                "sampled_softmax_with_cross_entropy("
                "use_customized_samples=True) needs customized_samples "
                "AND customized_probabilities")
        inputs["CustomizedSamples"] = customized_samples
        inputs["CustomizedProbabilities"] = customized_probabilities
    helper.append_op(
        "sample_logits", inputs=inputs,
        outputs={"Samples": samples, "Probabilities": probabilities,
                 "SampledLogits": sampled_logits_v,
                 "SampledLabels": sampled_labels_v},
        attrs={"num_samples": num_samples,
               "remove_accidental_hits": remove_accidental_hits,
               "seed": seed})
    return softmax_with_cross_entropy(sampled_logits_v, sampled_labels_v)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False):
    helper = LayerHelper("beam_search", name=name)
    selected_ids = helper.create_variable_for_type_inference("int64", True)
    selected_scores = helper.create_variable_for_type_inference(
        scores.dtype, True)
    parent_idx = helper.create_variable_for_type_inference("int64", True)
    helper.append_op(
        "beam_search",
        inputs={"pre_ids": pre_ids, "pre_scores": pre_scores,
                "ids": ids, "scores": scores},
        outputs={"selected_ids": selected_ids,
                 "selected_scores": selected_scores,
                 "parent_idx": parent_idx},
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level,
               "is_accumulated": is_accumulated})
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def gather_tree(ids, parents):
    """fluid.layers.gather_tree (gather_tree_op.cc)."""
    helper = LayerHelper("gather_tree")
    out = helper.create_variable_for_type_inference(ids.dtype)
    helper.append_op("gather_tree", {"Ids": ids, "Parents": parents},
                     {"Out": out}, {})
    return out


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """fluid.layers.warpctc (warpctc_op.cc) — padded [B, Tmax, C] logits +
    length tensors (the TPU replacement for LoD inputs)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference("float32")
    ins = {"Logits": input, "Label": label}
    if input_length is not None:
        ins["LogitsLength"] = input_length
    if label_length is not None:
        ins["LabelLength"] = label_length
    helper.append_op("warpctc", ins, {"Loss": loss},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None):
    """fluid.layers.ctc_greedy_decoder: argmax per step then ctc_align."""
    helper = LayerHelper("ctc_greedy_decoder")
    am = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int64")
    ins = {"Input": am}
    if input_length is not None:
        ins["InputLength"] = input_length
    helper.append_op("ctc_align", ins,
                     {"Output": out, "OutputLength": out_len},
                     {"blank": blank, "merge_repeated": True})
    if input_length is not None:
        return out, out_len
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """fluid.layers.linear_chain_crf (linear_chain_crf_op.cc); creates the
    [C+2, C] transition parameter (start/end rows + pairwise)."""
    helper = LayerHelper("linear_chain_crf")
    size = input.shape[-1]
    transition = helper.create_parameter(param_attr, [size + 2, size],
                                         "float32")
    ll = helper.create_variable_for_type_inference("float32")
    ins = {"Emission": input, "Transition": transition, "Label": label}
    if length is not None:
        ins["Length"] = length
    helper.append_op("linear_chain_crf", ins, {"LogLikelihood": ll}, {})
    return ll


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """fluid.layers.crf_decoding — pass `transition` (the parameter created
    by linear_chain_crf) or a param_attr naming it."""
    helper = LayerHelper("crf_decoding")
    if transition is None:
        size = input.shape[-1]
        transition = helper.create_parameter(param_attr, [size + 2, size],
                                             "float32")
    path = helper.create_variable_for_type_inference("int64")
    ins = {"Emission": input, "Transition": transition}
    if label is not None:
        ins["Label"] = label
    if length is not None:
        ins["Length"] = length
    helper.append_op("crf_decoding", ins, {"ViterbiPath": path}, {})
    return path


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, return_index=False, name=None):
    """fluid.layers.multiclass_nms — fixed-shape output [N, keep_top_k, 6]
    with label -1 padding + NmsRoisNum counts."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference("int32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out, "Index": index, "NmsRoisNum": num},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    if return_index:
        return out, index
    return out


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    """fluid.layers.anchor_generator (anchor_generator_op.cc)."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "anchor_generator", {"Input": input},
        {"Anchors": anchors, "Variances": variances},
        {"anchor_sizes": list(anchor_sizes or [64.0, 128.0, 256.0]),
         "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "stride": list(stride or [16.0, 16.0]), "offset": offset})
    return anchors, variances


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op("bipartite_match", {"DistMat": dist_matrix},
                     {"ColToRowMatchIndices": idx,
                      "ColToRowMatchDist": dist},
                     {"match_type": match_type or "bipartite",
                      "dist_threshold": dist_threshold or 0.5})
    return idx, dist


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": bbox_deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": variances},
        {"RpnRois": rois, "RpnRoiProbs": probs, "RpnRoisNum": num},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size})
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, name=None):
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x, "GTBox": gt_box, "GTLabel": gt_label}
    if gt_score is not None:
        ins["GTScore"] = gt_score
    helper.append_op("yolov3_loss", ins, {"Loss": loss},
                     {"anchors": list(anchors),
                      "anchor_mask": list(anchor_mask),
                      "class_num": class_num,
                      "ignore_thresh": ignore_thresh,
                      "downsample_ratio": downsample_ratio,
                      "use_label_smooth": use_label_smooth})
    return loss


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=False, name=None):
    """fluid.layers.matrix_nms (detection.py:3542; matrix_nms_op.cc) —
    fixed-shape [N, keep_top_k, 6] with label -1 padding."""
    helper = LayerHelper("matrix_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    index = helper.create_variable_for_type_inference("int32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("matrix_nms", {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out, "Index": index, "RoisNum": num},
                     {"score_threshold": score_threshold,
                      "post_threshold": post_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "use_gaussian": use_gaussian,
                      "gaussian_sigma": gaussian_sigma,
                      "background_label": background_label,
                      "normalized": normalized})
    rets = [out]
    if return_index:
        rets.append(index)
    if return_rois_num:
        rets.append(num)
    return rets[0] if len(rets) == 1 else tuple(rets)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """fluid.layers.locality_aware_nms (detection.py:3412) — EAST-style
    merge-then-NMS; fixed-shape [N, keep_top_k, 6]."""
    helper = LayerHelper("locality_aware_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op("locality_aware_nms",
                     {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out, "RoisNum": num},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "normalized": normalized,
                      "background_label": background_label})
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0, name=None):
    """fluid.layers.retinanet_detection_output (detection.py:3101) —
    multi-level decode + per-class NMS; fixed [N, keep_top_k, 6]."""
    helper = LayerHelper("retinanet_detection_output", name=name)
    out = helper.create_variable_for_type_inference(bboxes[0].dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "retinanet_detection_output",
        {"BBoxes": list(bboxes), "Scores": list(scores),
         "Anchors": list(anchors), "ImInfo": im_info},
        {"Out": out, "RoisNum": num},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold})
    return out


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """fluid.layers.target_assign (detection.py:1410; target_assign_op.h).
    input [N, B, K] padded gt rows; matched_indices [N, M]."""
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    wt = helper.create_variable_for_type_inference("float32")
    ins = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        ins["NegIndices"] = negative_indices
    helper.append_op("target_assign", ins,
                     {"Out": out, "OutWeight": wt},
                     {"mismatch_value": mismatch_value})
    return out, wt


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative",
                       name=None):
    """mine_hard_examples_op.cc — SSD OHEM; NegIndices [N, M] -1-padded."""
    helper = LayerHelper("mine_hard_examples", name=name)
    neg = helper.create_variable_for_type_inference("int32")
    upd = helper.create_variable_for_type_inference("int32")
    num = helper.create_variable_for_type_inference("int32")
    ins = {"ClsLoss": cls_loss, "MatchIndices": match_indices,
           "MatchDist": match_dist}
    if loc_loss is not None:
        ins["LocLoss"] = loc_loss
    helper.append_op("mine_hard_examples", ins,
                     {"NegIndices": neg, "UpdatedMatchIndices": upd,
                      "NegNum": num},
                     {"neg_pos_ratio": neg_pos_ratio,
                      "neg_dist_threshold": neg_dist_threshold,
                      "sample_size": sample_size,
                      "mining_type": mining_type})
    return neg, upd


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """fluid.layers.collect_fpn_proposals (detection.py:3869)."""
    helper = LayerHelper("collect_fpn_proposals", name=name)
    n = max_level - min_level + 1
    out = helper.create_variable_for_type_inference(multi_rois[0].dtype)
    num = helper.create_variable_for_type_inference("int32")
    ins = {"MultiLevelRois": list(multi_rois)[:n],
           "MultiLevelScores": list(multi_scores)[:n]}
    if rois_num_per_level is not None:
        ins["MultiLevelRoIsNum"] = list(rois_num_per_level)[:n]
    helper.append_op("collect_fpn_proposals", ins,
                     {"FpnRois": out, "RoisNum": num},
                     {"post_nms_topN": post_nms_top_n})
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """fluid.layers.distribute_fpn_proposals (detection.py:3669)."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n = max_level - min_level + 1
    multi = [helper.create_variable_for_type_inference(fpn_rois.dtype)
             for _ in range(n)]
    restore = helper.create_variable_for_type_inference("int32")
    nums = [helper.create_variable_for_type_inference("int32")
            for _ in range(n)]
    ins = {"FpnRois": fpn_rois}
    if rois_num is not None:
        ins["RoisNum"] = rois_num
    helper.append_op("distribute_fpn_proposals", ins,
                     {"MultiFpnRois": multi, "RestoreIndex": restore,
                      "MultiLevelRoIsNum": nums},
                     {"min_level": min_level, "max_level": max_level,
                      "refer_level": refer_level,
                      "refer_scale": refer_scale})
    return multi, restore


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=2.302585, name=None):
    """fluid.layers.box_decoder_and_assign (detection.py:3794)."""
    helper = LayerHelper("box_decoder_and_assign", name=name)
    decode = helper.create_variable_for_type_inference(target_box.dtype)
    assign = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op("box_decoder_and_assign",
                     {"PriorBox": prior_box, "PriorBoxVar": prior_box_var,
                      "TargetBox": target_box, "BoxScore": box_score},
                     {"DecodeBox": decode, "OutputAssignBox": assign},
                     {"box_clip": box_clip})
    return decode, assign


def polygon_box_transform(input, name=None):
    """fluid.layers.polygon_box_transform (detection.py:969)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("polygon_box_transform", {"Input": input},
                     {"Output": out}, {})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None):
    """fluid.layers.psroi_pool (nn.py:13759; psroi_pool_op.h).  rois are
    [R, 5] with a leading batch index (the padded-LoD redesign)."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("psroi_pool", {"X": input, "ROIs": rois},
                     {"Out": out},
                     {"output_channels": output_channels,
                      "spatial_scale": spatial_scale,
                      "pooled_height": pooled_height,
                      "pooled_width": pooled_width})
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """fluid.layers.prroi_pool (nn.py:13829; prroi_pool_op.h)."""
    helper = LayerHelper("prroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": input, "ROIs": rois}
    if batch_roi_nums is not None:
        ins["BatchRoINums"] = batch_roi_nums
    helper.append_op("prroi_pool", ins, {"Out": out},
                     {"spatial_scale": spatial_scale,
                      "pooled_height": pooled_height,
                      "pooled_width": pooled_width})
    return out


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """fluid.layers.roi_perspective_transform (detection.py:2508).  rois
    are [R, 9]: batch index + 4 quad corners."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    mat = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("roi_perspective_transform",
                     {"X": input, "ROIs": rois},
                     {"Out": out, "Mask": mask, "TransformMatrix": mat},
                     {"transformed_height": transformed_height,
                      "transformed_width": transformed_width,
                      "spatial_scale": spatial_scale})
    return out, mask, mat


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """fluid.layers.rpn_target_assign (detection.py:310) — emits sampled
    index/target tensors then gathers the matching predictions.  Gathers
    use clip-to-0 on the -1 padding; padded rows carry weight/label -1 so
    downstream losses mask them."""
    helper = LayerHelper("rpn_target_assign")
    loc_idx = helper.create_variable_for_type_inference("int32")
    score_idx = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference(bbox_pred.dtype)
    tgt_lbl = helper.create_variable_for_type_inference("int32")
    inw = helper.create_variable_for_type_inference(bbox_pred.dtype)
    loc_n = helper.create_variable_for_type_inference("int32")
    score_n = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "rpn_target_assign",
        {"Anchor": anchor_box, "GtBoxes": gt_boxes, "IsCrowd": is_crowd,
         "ImInfo": im_info},
        {"LocationIndex": loc_idx, "ScoreIndex": score_idx,
         "TargetBBox": tgt_bbox, "TargetLabel": tgt_lbl,
         "BBoxInsideWeight": inw, "LocCount": loc_n,
         "ScoreCount": score_n},
        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
         "rpn_straddle_thresh": rpn_straddle_thresh,
         "rpn_fg_fraction": rpn_fg_fraction,
         "rpn_positive_overlap": rpn_positive_overlap,
         "rpn_negative_overlap": rpn_negative_overlap,
         "use_random": use_random})
    pred_loc = gather(reshape(bbox_pred, [-1, 4]), relu(loc_idx))
    pred_score = gather(reshape(cls_logits, [-1, 1]), relu(score_idx))
    return (pred_score, pred_loc, tgt_lbl, tgt_bbox, inw)


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """fluid.layers.retinanet_target_assign (detection.py:69)."""
    helper = LayerHelper("retinanet_target_assign")
    loc_idx = helper.create_variable_for_type_inference("int32")
    score_idx = helper.create_variable_for_type_inference("int32")
    tgt_bbox = helper.create_variable_for_type_inference(bbox_pred.dtype)
    tgt_lbl = helper.create_variable_for_type_inference("int32")
    inw = helper.create_variable_for_type_inference(bbox_pred.dtype)
    fg_num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "retinanet_target_assign",
        {"Anchor": anchor_box, "GtBoxes": gt_boxes, "GtLabels": gt_labels,
         "IsCrowd": is_crowd, "ImInfo": im_info},
        {"LocationIndex": loc_idx, "ScoreIndex": score_idx,
         "TargetBBox": tgt_bbox, "TargetLabel": tgt_lbl,
         "BBoxInsideWeight": inw, "ForegroundNumber": fg_num},
        {"positive_overlap": positive_overlap,
         "negative_overlap": negative_overlap})
    pred_loc = gather(reshape(bbox_pred, [-1, 4]), relu(loc_idx))
    pred_score = gather(reshape(cls_logits, [-1, num_classes]),
                        relu(score_idx))
    return (pred_score, pred_loc, tgt_lbl, tgt_bbox, inw, fg_num)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """fluid.layers.generate_proposal_labels (detection.py:2600)."""
    helper = LayerHelper("generate_proposal_labels")
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    tgt = helper.create_variable_for_type_inference(rpn_rois.dtype)
    inw = helper.create_variable_for_type_inference(rpn_rois.dtype)
    outw = helper.create_variable_for_type_inference(rpn_rois.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_proposal_labels",
        {"RpnRois": rpn_rois, "GtClasses": gt_classes,
         "IsCrowd": is_crowd, "GtBoxes": gt_boxes, "ImInfo": im_info},
        {"Rois": rois, "LabelsInt32": labels, "BboxTargets": tgt,
         "BboxInsideWeights": inw, "BboxOutsideWeights": outw,
         "RoisNum": num},
        {"batch_size_per_im": batch_size_per_im,
         "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
         "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
         "bbox_reg_weights": list(bbox_reg_weights),
         "class_nums": class_nums or 81, "use_random": use_random,
         "is_cls_agnostic": is_cls_agnostic,
         "is_cascade_rcnn": is_cascade_rcnn})
    return rois, labels, tgt, inw, outw


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """fluid.layers.generate_mask_labels (detection.py:2738).  gt_segms is
    the padded polygon nest [N, B, V, 2] (NaN-padded vertices)."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = helper.create_variable_for_type_inference(rois.dtype)
    has_mask = helper.create_variable_for_type_inference("int32")
    mask = helper.create_variable_for_type_inference("int32")
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_mask_labels",
        {"ImInfo": im_info, "GtClasses": gt_classes, "IsCrowd": is_crowd,
         "GtSegms": gt_segms, "Rois": rois,
         "LabelsInt32": labels_int32},
        {"MaskRois": mask_rois, "RoiHasMaskInt32": has_mask,
         "MaskInt32": mask, "MaskRoisNum": num},
        {"num_classes": num_classes, "resolution": resolution})
    return mask_rois, has_mask, mask


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None,
                  out_states=None, ap_version="integral"):
    """fluid.layers.detection_map (detection.py:1224) — VOC mAP with
    accumulation state; padded DetectRes [N, D, 6] / Label [N, G, 5|6]."""
    helper = LayerHelper("detection_map")
    m_ap = helper.create_variable_for_type_inference("float32")
    if out_states is not None:
        # the caller's accumulation variables receive the updated state
        # (reference detection.py contract driven by the DetectionMAP
        # metric: out_states aliases input_states across batches)
        pc, tp, fp = out_states
    else:
        pc = helper.create_variable_for_type_inference("float32")
        tp = helper.create_variable_for_type_inference("float32")
        fp = helper.create_variable_for_type_inference("float32")
    ins = {"DetectRes": detect_res, "Label": label}
    if has_state is not None:
        ins["HasState"] = has_state
    if input_states is not None:
        ins["PosCount"], ins["TruePos"], ins["FalsePos"] = input_states
    helper.append_op(
        "detection_map", ins,
        {"AccumPosCount": pc, "AccumTruePos": tp, "AccumFalsePos": fp,
         "MAP": m_ap},
        {"class_num": class_num, "background_label": background_label,
         "overlap_threshold": overlap_threshold,
         "evaluate_difficult": evaluate_difficult,
         "ap_type": ap_version})
    return m_ap


def continuous_value_model(input, cvm, use_cvm=True):
    """fluid.layers.continuous_value_model (nn.py:14026; cvm_op.h) —
    show/click counter transform ahead of the CTR tower."""
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cvm", {"X": input, "CVM": cvm}, {"Y": out},
                     {"use_cvm": use_cvm})
    return out


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """fluid.layers.filter_by_instag (nn.py:10140) — padded redesign:
    kept rows pass through, dropped rows zeroed + LossWeight 0."""
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    lw = helper.create_variable_for_type_inference("float32")
    imap = helper.create_variable_for_type_inference("int64")
    helper.append_op("filter_by_instag",
                     {"Ins": ins, "Ins_tag": ins_tag,
                      "Filter_tag": filter_tag},
                     {"Out": out, "LossWeight": lw, "IndexMap": imap},
                     {"is_lod": is_lod,
                      "out_val_if_empty": out_val_if_empty})
    return out, lw


def hash(input, hash_size, num_hash=1, name=None):
    """fluid.layers.hash (nn.py:12917; hash_op.h)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("hash", {"X": input}, {"Out": out},
                     {"mod_by": hash_size, "num_hash": num_hash})
    return out


def shuffle_batch(x, seed=None):
    """fluid.contrib.layers.shuffle_batch (contrib/layers/nn.py:785)."""
    helper = LayerHelper("shuffle_batch")
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference("int64")
    seed_out = helper.create_variable_for_type_inference("int64")
    ins = {"X": x}
    attrs = {}  # op_uid auto-assigned by Program.append (program.py:290)
    if seed is not None:
        if isinstance(seed, int):
            attrs["startup_seed"] = seed
        else:
            ins["Seed"] = seed
    helper.append_op("shuffle_batch", ins,
                     {"Out": out, "ShuffleIdx": idx, "SeedOut": seed_out},
                     attrs)
    return out


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent=0.0, is_training=True,
                        use_filter=False, white_list_len=0,
                        black_list_len=0, seed=0, lr=1.0, param_attr=None,
                        param_attr_wl=None, param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype="float32"):
    """fluid.contrib.layers.search_pyramid_hash (contrib nn.py:669;
    pyramid_hash_op.cc).  input [B, S] padded token ids."""
    helper = LayerHelper("pyramid_hash", name=name)
    w = helper.create_parameter(param_attr, [space_len + rand_len], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    drop = helper.create_variable_for_type_inference("int32")
    helper.append_op("pyramid_hash", {"X": input, "W": w},
                     {"Out": out, "DropPos": drop},
                     {"num_emb": num_emb, "space_len": space_len,
                      "pyramid_layer": pyramid_layer,
                      "rand_len": rand_len, "lr": lr,
                      "drop_out_percent": drop_out_percent})
    return out


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """fluid.contrib.layers.tdm_child (contrib nn.py:1019) — the
    TreeInfo table is a learnable-shaped parameter the caller fills via
    initializer (same contract as the reference's embedding-style
    param)."""
    helper = LayerHelper("tdm_child")
    info = helper.create_parameter(param_attr, [node_nums, 3 + child_nums],
                                   "int32")
    child = helper.create_variable_for_type_inference(dtype)
    mask = helper.create_variable_for_type_inference(dtype)
    helper.append_op("tdm_child", {"X": x, "TreeInfo": info},
                     {"Child": child, "LeafMask": mask},
                     {"child_nums": child_nums})
    return child, mask


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=True, seed=0,
                tree_dtype="int32", dtype="int32"):
    """fluid.contrib.layers.tdm_sampler (contrib nn.py:1104)."""
    helper = LayerHelper("tdm_sampler")
    n_layers = len(layer_node_num_list)
    travel = helper.create_parameter(tree_travel_attr,
                                     [leaf_node_num, n_layers], tree_dtype)
    layer = helper.create_parameter(tree_layer_attr,
                                    [n_layers, max(layer_node_num_list)],
                                    tree_dtype)
    out = helper.create_variable_for_type_inference(dtype)
    labels = helper.create_variable_for_type_inference(dtype)
    mask = helper.create_variable_for_type_inference(dtype)
    helper.append_op("tdm_sampler",
                     {"X": x, "Travel": travel, "Layer": layer},
                     {"Out": out, "Labels": labels, "Mask": mask},
                     {"neg_samples_num_list": list(neg_samples_num_list),
                      "layer_node_num_list": list(layer_node_num_list),
                      "output_positive": output_positive, "seed": seed})
    return out, labels, mask


def switch_moe(x, num_experts, d_hidden, capacity_factor=1.25,
               ep_ring_id=None, param_attr=None, name=None):
    """Switch (top-1) Mixture-of-Experts feed-forward as a static-graph
    layer (VERDICT r3: MoE as a framework citizen).  Shares the
    incubate/moe.py core; under a mesh executor `ep_ring_id` binds the
    expert axis to a mesh axis so dispatch rides all_to_all over ICI.
    x [..., D] -> (out [..., D], aux_loss scalar)."""
    helper = LayerHelper("switch_moe", name=name)
    d_model = int(x.shape[-1])

    def _sub_attr(suffix):
        return ParamAttr.derive(param_attr, suffix)

    gate_w = helper.create_parameter(_sub_attr("_gate"),
                                     [d_model, num_experts], x.dtype)
    w1 = helper.create_parameter(_sub_attr("_w1"),
                                 [num_experts, d_model, d_hidden], x.dtype)
    b1 = helper.create_parameter(None, [num_experts, d_hidden], x.dtype,
                                 is_bias=True)
    w2 = helper.create_parameter(_sub_attr("_w2"),
                                 [num_experts, d_hidden, d_model], x.dtype)
    b2 = helper.create_parameter(None, [num_experts, d_model], x.dtype,
                                 is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    attrs = {"capacity_factor": capacity_factor}
    if ep_ring_id is not None:
        attrs["ep_ring_id"] = int(ep_ring_id)
    helper.append_op("switch_moe",
                     {"X": x, "GateW": gate_w, "W1": w1, "B1": b1,
                      "W2": w2, "B2": b2},
                     {"Out": out, "AuxLoss": aux}, attrs)
    return out, aux


def crop(x, shape=None, offsets=None, name=None):
    """fluid.layers.crop (crop_op.h)."""
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x}
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Y"] = shape
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = offsets
    helper.append_op("crop", ins, {"Out": out}, attrs)
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    """fluid.layers.crop_tensor (crop_tensor_op.h)."""
    helper = LayerHelper("crop_tensor", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x}
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Shape"] = shape
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = offsets
    helper.append_op("crop_tensor", ins, {"Out": out}, attrs)
    return out


def similarity_focus(input, axis, indexes, name=None):
    """fluid.layers.similarity_focus (similarity_focus_op.h)."""
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("similarity_focus", {"X": input}, {"Out": out},
                     {"axis": axis, "indexes": list(indexes)})
    return out


def fsp_matrix(x, y):
    """fluid.layers.fsp_matrix (fsp_op.h) — distillation FSP matrix."""
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fsp", {"X": x, "Y": y}, {"Out": out}, {})
    return out


def conv_shift_layer(x, y, name=None):
    """fluid.contrib: conv_shift circular correlation (conv_shift_op.cc)."""
    helper = LayerHelper("conv_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("conv_shift", {"X": x, "Y": y}, {"Out": out}, {})
    return out


def modified_huber_loss(input, label):
    """modified_huber_loss_op.h."""
    helper = LayerHelper("modified_huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    inter = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("modified_huber_loss", {"X": input, "Y": label},
                     {"Out": out, "IntermediateVal": inter}, {})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """fluid.layers.teacher_student_sigmoid_loss."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     {"X": input, "Label": label}, {"Y": out},
                     {"soft_max_up_bound": soft_max_up_bound,
                      "soft_max_lower_bound": soft_max_lower_bound})
    return out


def positive_negative_pair(score, label, query_id, weight=None, column=-1):
    """positive_negative_pair_op.h — LTR pair-order metric."""
    helper = LayerHelper("positive_negative_pair")
    pos = helper.create_variable_for_type_inference("float32")
    neg = helper.create_variable_for_type_inference("float32")
    neu = helper.create_variable_for_type_inference("float32")
    ins = {"Score": score, "Label": label, "QueryID": query_id}
    if weight is not None:
        ins["Weight"] = weight
    helper.append_op("positive_negative_pair", ins,
                     {"PositivePair": pos, "NegativePair": neg,
                      "NeutralPair": neu}, {"column": column})
    return pos, neg, neu


def sequence_scatter(input, index, updates, name=None):
    """fluid.layers.sequence_scatter — padded redesign: index/updates
    [B, S] with -1 padding."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_scatter",
                     {"X": input, "Ids": index, "Updates": updates},
                     {"Out": out}, {})
    return out


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """sequence_topk_avg_pooling_op.h — X [B, C, R, L] + row/col
    lengths."""
    helper = LayerHelper("sequence_topk_avg_pooling")
    out = helper.create_variable_for_type_inference(input.dtype)
    pos = helper.create_variable_for_type_inference("int32")
    helper.append_op("sequence_topk_avg_pooling",
                     {"X": input, "ROW": row, "COLUMN": col},
                     {"Out": out, "pos": pos},
                     {"topks": list(topks), "channel_num": channel_num})
    return out


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, x_len=None,
                        y_len=None):
    """fluid.contrib.layers.match_matrix_tensor
    (match_matrix_tensor_op.cc); padded [B, L, D] inputs."""
    helper = LayerHelper("match_matrix_tensor", name=name)
    d = int(x.shape[-1])
    w = helper.create_parameter(param_attr, [d, channel_num, d], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    tmp = helper.create_variable_for_type_inference(dtype)
    ins = {"X": x, "Y": y, "W": w}
    if x_len is not None:
        ins["XLen"] = x_len
    if y_len is not None:
        ins["YLen"] = y_len
    helper.append_op("match_matrix_tensor", ins,
                     {"Out": out, "Tmp": tmp},
                     {"dim_t": channel_num})
    if act is not None:
        return helper.append_activation(out, act)
    return out


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """fluid.contrib.layers.var_conv_2d (var_conv_2d_op.cc); padded
    [B, C, H, W] + row/col lengths."""
    helper = LayerHelper("var_conv_2d", name=name)
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    st = stride if isinstance(stride, (list, tuple)) else [stride, stride]
    w = helper.create_parameter(
        param_attr, [output_channel, input_channel * fs[0] * fs[1]],
        dtype)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": input, "W": w}
    if row is not None:
        ins["ROW"] = row
    if col is not None:
        ins["COLUMN"] = col
    helper.append_op("var_conv_2d", ins, {"Out": out},
                     {"kernel_h": fs[0], "kernel_w": fs[1],
                      "stride_h": st[0], "stride_w": st[1],
                      "output_channel": output_channel,
                      "input_channel": input_channel})
    if act is not None:
        return helper.append_activation(out, act)
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """fluid.contrib.layers.tree_conv (tree_conv_op.h) — TBCNN layer."""
    helper = LayerHelper("tree_conv", name=name)
    feature = int(nodes_vector.shape[-1])
    w = helper.create_parameter(param_attr,
                                [feature, 3, output_size, num_filters],
                                nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(nodes_vector.dtype)
    helper.append_op("tree_conv",
                     {"NodesVector": nodes_vector, "EdgeSet": edge_set,
                      "Filter": w},
                     {"Out": out}, {"max_depth": max_depth})
    if bias_attr:
        b = helper.create_parameter(bias_attr,
                                    [output_size, num_filters],
                                    nodes_vector.dtype, is_bias=True)
        out2 = helper.create_variable_for_type_inference(
            nodes_vector.dtype)
        helper.append_op("elementwise_add", {"X": out, "Y": b},
                         {"Out": out2}, {"axis": -1})
        out = out2
    if act is not None:
        return helper.append_activation(out, act)
    return out


def attention_lstm(x, c0, attention_weight, lstm_weight, lstm_bias,
                   h0=None, attention_bias=None, seq_len=None,
                   gate_activation="sigmoid", cell_activation="tanh",
                   candidate_activation="tanh", name=None):
    """attention_lstm_op.cc — fused attention LSTM over padded [B, T, M]
    input (optional seq_len masks padding)."""
    helper = LayerHelper("attention_lstm", name=name)
    hidden = helper.create_variable_for_type_inference(x.dtype)
    cell = helper.create_variable_for_type_inference(x.dtype)
    ax = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": x, "C0": c0, "AttentionWeight": attention_weight,
           "LSTMWeight": lstm_weight, "LSTMBias": lstm_bias}
    if h0 is not None:
        ins["H0"] = h0
    if attention_bias is not None:
        ins["AttentionBias"] = attention_bias
    if seq_len is not None:
        ins["SeqLen"] = seq_len
    helper.append_op("attention_lstm", ins,
                     {"Hidden": hidden, "Cell": cell, "AttentionedX": ax},
                     {"gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation})
    return hidden, cell


def inplace_abn(input, scale, bias, mean, variance, activation="identity",
                alpha=0.01, momentum=0.9, epsilon=1e-5, is_test=False):
    """inplace_abn_op.cc — fused BN + activation (buffer reuse is XLA's
    job here, numerics identical)."""
    helper = LayerHelper("inplace_abn")
    y = helper.create_variable_for_type_inference(input.dtype)
    outs = {"Y": y}
    for s in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        outs[s] = helper.create_variable_for_type_inference("float32")
    helper.append_op("inplace_abn",
                     {"X": input, "Scale": scale, "Bias": bias,
                      "Mean": mean, "Variance": variance}, outs,
                     {"activation": activation, "alpha": alpha,
                      "momentum": momentum, "epsilon": epsilon,
                      "is_test": is_test})
    return y


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """fluid.layers.py_func (py_func_op.cc) — run a host-python function as
    an op; lowers to jax.pure_callback so it composes with jit.  The
    backward_func receives (forward inputs + forward outputs + out grads)
    minus skip_vars_in_backward_input, matching the reference contract."""
    from ..ops.kernels.decode import register_py_func
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    attrs = {"func_id": register_py_func(func),
             "out_shapes": [list(o.shape) for o in outs],
             "out_dtypes": [o.dtype or "float32" for o in outs]}
    if backward_func is not None:
        attrs["backward_func_id"] = register_py_func(backward_func)
        skip_names = {v.name if hasattr(v, "name") else str(v)
                      for v in (skip_vars_in_backward_input or [])}
        ordered = [v.name for v in list(xs) + list(outs)]
        attrs["backward_skip_ins"] = [i for i, n in enumerate(ordered)
                                      if n in skip_names]
    helper.append_op("py_func", {"X": list(xs)}, {"Out": list(outs)},
                     attrs)
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, is_reverse=False, name=None):
    """fluid.layers.dynamic_lstm (lstm_op.cc) over padded dense input.

    `input` is the pre-projected gate sequence [batch, time, size] with
    ``size = 4 * hidden`` (caller projects with an fc, matching the
    reference contract); returns (hidden, cell) each [batch, time, hidden].
    """
    helper = LayerHelper("dynamic_lstm", name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, [hidden, 4 * hidden], input.dtype)
    b = helper.create_parameter(bias_attr, [1, 4 * hidden], input.dtype,
                                is_bias=True)
    h = helper.create_variable_for_type_inference(input.dtype)
    c = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Weight": w}
    if b is not None:
        ins["Bias"] = b
    if h_0 is not None:
        ins["H0"] = h_0
    if c_0 is not None:
        ins["C0"] = c_0
    helper.append_op("lstm", inputs=ins,
                     outputs={"Hidden": h, "Cell": c, "BatchGate": gate,
                              "BatchCellPreAct": pre},
                     attrs={"is_reverse": is_reverse})
    return h, c


def dynamic_gru(input, size, h_0=None, param_attr=None, bias_attr=None,
                is_reverse=False, name=None):
    """fluid.layers.dynamic_gru (gru_op.cc) over padded dense input
    [batch, time, 3*size]; returns hidden [batch, time, size]."""
    helper = LayerHelper("dynamic_gru", name=name)
    w = helper.create_parameter(param_attr, [size, 3 * size], input.dtype)
    b = helper.create_parameter(bias_attr, [1, 3 * size], input.dtype,
                                is_bias=True)
    h = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    rhp = helper.create_variable_for_type_inference(input.dtype)
    bh = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Weight": w}
    if b is not None:
        ins["Bias"] = b
    if h_0 is not None:
        ins["H0"] = h_0
    helper.append_op("gru", inputs=ins,
                     outputs={"Hidden": h, "BatchGate": gate,
                              "BatchResetHiddenPrev": rhp,
                              "BatchHidden": bh},
                     attrs={"is_reverse": is_reverse})
    return h


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False, name=None):
    """fluid.layers.gru_unit (gru_unit_op.cc): one GRU step.  `input` is
    the pre-projected gate input [batch, size] with size = 3 * d, `hidden`
    the previous state [batch, d].  Returns (updated_hidden,
    reset_hidden_pre, gate) — reference order."""
    if size % 3 != 0:
        raise ValueError(
            f"gru_unit: size must be 3 * hidden_dim, got {size}")
    d = size // 3
    helper = LayerHelper("gru_unit", name=name)
    w = helper.create_parameter(param_attr, [d, 3 * d], input.dtype)
    b = helper.create_parameter(bias_attr, [1, 3 * d], input.dtype,
                                is_bias=True)
    gate = helper.create_variable_for_type_inference(input.dtype)
    rhp = helper.create_variable_for_type_inference(input.dtype)
    h = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "HiddenPrev": hidden, "Weight": w}
    if b is not None:
        ins["Bias"] = b
    helper.append_op("gru_unit", inputs=ins,
                     outputs={"Gate": gate, "ResetHiddenPrev": rhp,
                              "Hidden": h},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation,
                            "origin_mode": origin_mode})
    return h, rhp, gate


def sequence_pool(input, pool_type, length=None, name=None):
    """fluid.layers.sequence_pool (sequence_pool_op.cc): pool over the time
    axis of padded [batch, time, d] input; `length` masks the padding."""
    helper = LayerHelper("sequence_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": input}
    if length is not None:
        ins["Length"] = length
    helper.append_op("sequence_pool", inputs=ins, outputs={"Out": out},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding_start=None, param_attr=None, bias_attr=None,
                  act=None, name=None):
    """fluid.layers.sequence_conv (sequence_conv_op.cc) on padded input."""
    if filter_stride != 1:
        # the kernel computes stride-1 context windows (so does the
        # reference op: sequence_conv_op.cc enforces contextStride == 1)
        raise ValueError("sequence_conv only supports filter_stride=1")
    helper = LayerHelper("sequence_conv", name=name)
    w = helper.create_parameter(
        param_attr, [filter_size * input.shape[-1], num_filters], input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    start = -(filter_size // 2) if padding_start is None else padding_start
    helper.append_op("sequence_conv",
                     inputs={"X": input, "Filter": w},
                     outputs={"Out": out},
                     attrs={"contextLength": filter_size,
                            "contextStart": start,
                            "contextStride": filter_stride})
    b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                is_bias=True)
    if b is not None:
        tmp = helper.create_variable_for_type_inference(out.dtype)
        helper.append_op("elementwise_add", inputs={"X": out, "Y": b},
                         outputs={"Out": tmp},
                         attrs={"axis": len(out.shape) - 1})
        out = tmp
    return helper.append_activation(out, act)


def cos_sim(X, Y, name=None):
    """fluid.layers.cos_sim (cos_sim_op.cc)."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": X, "Y": Y},
                     outputs={"Out": out, "XNorm": xn, "YNorm": yn}, attrs={})
    return out


# ---------------------------------------------------------------------------
# control flow (fluid.layers.control_flow parity; see static/control_flow.py)
# ---------------------------------------------------------------------------
from .control_flow import (  # noqa: E402,F401
    While, while_loop, cond, case, switch_case, Switch, StaticRNN,
    DynamicRNN, array_write, array_read, array_length, create_array,
    lod_rank_table, max_sequence_len, lod_tensor_to_array,
    array_to_lod_tensor, reorder_lod_tensor_by_rank, shrink_memory,
    split_lod_tensor, merge_lod_tensor)


def sequence_last_step(input, length=None):
    """fluid.layers.sequence_last_step (sequence_lod.py) — last real step
    of each padded sequence; `length` marks where padding starts."""
    return sequence_pool(input, "last", length=length)


def sequence_first_step(input, length=None):
    """fluid.layers.sequence_first_step (sequence_lod.py)."""
    return sequence_pool(input, "first", length=length)


__all__ += ["dynamic_lstm", "dynamic_gru", "sequence_pool", "sequence_conv",
            "cos_sim", "gru_unit",
            "sequence_last_step", "sequence_first_step",
            "While", "while_loop", "cond", "case", "switch_case", "Switch",
            "StaticRNN", "DynamicRNN",
            "array_write", "array_read", "array_length", "create_array",
            "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
            "array_to_lod_tensor", "reorder_lod_tensor_by_rank",
            "shrink_memory", "split_lod_tensor", "merge_lod_tensor",
            "gather_tree", "warpctc", "ctc_greedy_decoder",
            "linear_chain_crf", "crf_decoding", "multiclass_nms",
            "anchor_generator", "bipartite_match", "generate_proposals",
            "yolov3_loss", "py_func"]


# ---------------------------------------------------------------------------
# auto-generated tail: one layer fn per mechanically-shaped registered op
# (fluid layer_function_generator.py analog; see static/layer_generator.py)
# ---------------------------------------------------------------------------
from .layer_generator import generate_layer_fns as _generate_layer_fns  # noqa: E402

_GENERATED_LAYERS = _generate_layer_fns(globals(), dir())
__all__ += _GENERATED_LAYERS
__all__ += ["mean_iou", "Print", "square_error_cost", "conv3d_transpose", "data_norm", "multi_box_head"]


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  name=None, h_0=None, c_0=None, proj_param_attr=None):
    """fluid.layers.dynamic_lstmp (lstmp_op.cc): LSTM with recurrent
    projection over padded dense input [b, t, 4*hidden] (size = 4*hidden,
    caller pre-projects with an fc, same contract as dynamic_lstm).
    Returns (projection [b,t,proj_size], cell [b,t,hidden]).

    param_attr configures the [proj, 4*hidden] recurrent weight;
    proj_param_attr the [hidden, proj] projection weight (it gets only a
    derived NAME from param_attr when unset — initializers are
    shape-specific and must not be shared across differently-shaped
    weights)."""
    helper = LayerHelper("dynamic_lstmp", name=name)
    hidden = size // 4
    w = helper.create_parameter(param_attr, [proj_size, 4 * hidden],
                                input.dtype)
    if proj_param_attr is None and isinstance(param_attr, ParamAttr) \
            and param_attr.name:
        proj_param_attr = ParamAttr(name=param_attr.name + "_proj")
    pw = helper.create_parameter(proj_param_attr, [hidden, proj_size],
                                 input.dtype)
    # peepholes (the reference lstmp default): bias widens to 7*hidden —
    # 4d gate bias + the W_ic/W_if/W_oc diagonal peephole weights
    b_width = 7 * hidden if use_peepholes else 4 * hidden
    b = helper.create_parameter(bias_attr, [1, b_width], input.dtype,
                                is_bias=True)
    proj = helper.create_variable_for_type_inference(input.dtype)
    cell = helper.create_variable_for_type_inference(input.dtype)
    gate = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    hid = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Weight": w, "ProjWeight": pw}
    if b is not None:
        ins["Bias"] = b
    if h_0 is not None:
        ins["H0"] = h_0
    if c_0 is not None:
        ins["C0"] = c_0
    helper.append_op("lstmp", inputs=ins,
                     outputs={"Projection": proj, "Cell": cell,
                              "BatchGate": gate, "BatchCellPreAct": pre,
                              "BatchHidden": hid},
                     attrs={"is_reverse": is_reverse,
                            "use_peepholes": use_peepholes,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    return proj, cell


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """fluid.layers.chunk_eval (chunk_eval_op.cc:22): chunking
    precision/recall/F1 over IOB/IOE/IOBES/plain tag schemes; padded
    [B, T] sequences with optional seq_length (the LoD replacement)."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference("float32", True)
    recall = helper.create_variable_for_type_inference("float32", True)
    f1 = helper.create_variable_for_type_inference("float32", True)
    n_inf = helper.create_variable_for_type_inference("int64", True)
    n_lab = helper.create_variable_for_type_inference("int64", True)
    n_corr = helper.create_variable_for_type_inference("int64", True)
    ins = {"Inference": input, "Label": label}
    if seq_length is not None:
        ins["SeqLength"] = seq_length
    helper.append_op(
        "chunk_eval", inputs=ins,
        outputs={"Precision": precision, "Recall": recall,
                 "F1-Score": f1, "NumInferChunks": n_inf,
                 "NumLabelChunks": n_lab, "NumCorrectChunks": n_corr},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": list(excluded_chunk_types or [])})
    return precision, recall, f1, n_inf, n_lab, n_corr


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """fluid.layers.deformable_conv (deformable_conv_op.cc:108):
    modulated (v2) when mask is given, v1 otherwise."""
    helper = LayerHelper("deformable_conv", name=name)
    c_in = input.shape[1]
    ks = _pair(filter_size)
    w = helper.create_parameter(
        param_attr, [num_filters, c_in // groups] + ks, input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"Input": input, "Offset": offset, "Filter": w}
    op_type = "deformable_conv_v1"
    if modulated:
        if mask is None:
            raise ValueError("modulated deformable_conv needs mask "
                             "(use modulated=False for v1)")
        ins["Mask"] = mask
        op_type = "deformable_conv"
    helper.append_op(
        op_type, inputs=ins, outputs={"Output": out},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups,
               "deformable_groups": deformable_groups,
               "im2col_step": im2col_step})
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                    is_bias=True)
        out2 = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add",
                         inputs={"X": out, "Y": b},
                         outputs={"Out": out2}, attrs={"axis": 1})
        return out2
    return out


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, flatten_to_2d=False,
                      name=None):
    """fluid.layers.density_prior_box (density_prior_box_op.h:23)."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32", True)
    vars_ = helper.create_variable_for_type_inference("float32", True)
    steps = steps or [0.0, 0.0]
    helper.append_op(
        "density_prior_box",
        inputs={"Input": input, "Image": image},
        outputs={"Boxes": boxes, "Variances": vars_},
        attrs={"densities": [int(d) for d in (densities or [])],
               "fixed_sizes": [float(s) for s in (fixed_sizes or [])],
               "fixed_ratios": [float(r) for r in (fixed_ratios or [])],
               "variances": [float(v) for v in
                             (variance or [0.1, 0.1, 0.2, 0.2])],
               "clip": clip, "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": float(offset)})
    if flatten_to_2d:
        boxes = reshape(boxes, [-1, 4])
        vars_ = reshape(vars_, [-1, 4])
    return boxes, vars_

"""Auto-parallel planner: compile-time cost-model search over the three
static-analysis substrates.

Closes the ROADMAP loop the previous tiers opened one leg at a time:

  * HBM      — `static.analyze_program` (PR "memory tier"): op-IR
               liveness walk, prediction == applied under dp_shard.
  * wire     — `static.collective_wire_bytes` (PR "verifier tier"):
               ordered collective schedule with ring accounting.
  * compute  — `static.analyze_flops` (PR "telemetry tier"): per-op
               FLOPs walk that prices rewrites (remat replays, ring
               degradation) the analytic 6N formula cannot see.

Until now these estimators answered questions a HUMAN asked — the
docs/perf.md decision table was hand-tuned by a reviewer reading them.
`plan_program` asks all the questions itself: it enumerates the knob
lattice (batch bucket × remat × ZeRO dp_shard degree × ZeRO stage 1/2/3
× gradient-merge K × shard bucket-MB × ring-attention variant), applies
each candidate
as a REAL program rewrite on a clone (every knob already is one:
`recompute_rewrite.apply_recompute`, `sharding.shard_optimizer_states`,
`static.gradient_merge`, `insert_grad_allreduce`; ring rides as a
pre-built program variant because `nets.scaled_dot_product_attention`
emits the op at build time), prices it with an overlap-aware roofline,
gates feasibility on the HBM walker and correctness on
`static.check_program(level="collective")` — the search space never
contains a deadlocking plan — and returns the argmax `Plan`.

Roofline (per chip, per dispatched step):

    compute_s      = walked FLOPs / peak_flops_per_chip("tpu")
    wire_overlap_s = ring-accounted bytes of the gradient REDUCTION
                     collectives / ICI bandwidth   (XLA overlaps these
                     with backward compute)
    wire_serial_s  = everything else (the allgather publish runs after
                     the sharded update; forward collectives sit on the
                     critical path) / ICI bandwidth
    step_s         = max(compute_s, wire_overlap_s) + wire_serial_s

This is a RANKING model, not a wall-clock oracle: it assumes peak MXU
rate, so absolute times are lower bounds — but a constant efficiency
factor cancels in the argmax, which is all the planner needs (the same
reasoning the analytic MFU accounting has always used).  The objective
is samples/sec/chip = batch / step_s: at equal step time the bigger
feasible batch wins, which is exactly the measured r5 result (b64 at
36.7% MFU vs b32 at 15.5%).

Knobs the model deliberately prices as no-wins so the trace shows WHY:
gradient_merge runs its (masked) commit and its reduction every
micro-step in the LOOPED dispatch, so alone it never improves predicted
throughput — it exists to hit an EFFECTIVE batch a bigger per-chip
batch can't fit, and the trace table says so instead of hiding it.
The `scan_hoist` knob changes that: under the scanned-window dispatch
(`distributed/scan_window.split_commit_tail`) the commit tail — the
optimizer update and the ZeRO publish allgather — runs ONCE per
K-step window instead of every micro-step, so the publish-role wire
bytes price at 1/K and a gm×ZeRO candidate can win on wire, not just
on effective batch.

The roofline is a RANKING model by default; `calibrate(pairs)` fits
per-class efficiency coefficients (compute, overlappable wire, serial
wire, plus a per-dispatch overhead intercept) from (predicted
component, measured step) pairs so `predicted_step_ms` approaches
wall-clock on the calibrated host.  `tools/calibrate_roofline.py`
produces the pairs on the local mesh and checks the fit in at
``perf_r05/roofline_calibration.json``; `plan_program` loads it
automatically once its residual is under
`DEFAULT_CALIBRATION_RESIDUAL_PCT` (opt out with
``PADDLE_TPU_ROOFLINE_CALIBRATION=0``, or point the env at another
fit).

`apply_plan(program, startup, plan)` applies the chosen knobs to the
real program, recording the plan in the `core/pass_framework`
applied-passes registry first — the verifier's V504 plan-drift check
then flags any later hand-edit whose applied passes disagree with the
recorded plan.  `bench.py --auto` is the end-to-end wiring: plan, apply,
run on the local mesh.
"""
from __future__ import annotations

import itertools
import math
import os
from typing import Dict, List, Optional, Tuple

from ..core.compile_cache import next_pow2 as _next_pow2
from ..core.program import Program

__all__ = ["Plan", "plan_program", "apply_plan", "ici_bytes_per_chip",
           "page_budget", "ICI_ENV", "DEFAULT_ICI_BYTES_PER_S",
           "Calibration", "calibrate", "default_calibration",
           "CALIBRATION_ENV", "DEFAULT_CALIBRATION_RESIDUAL_PCT"]

ICI_ENV = "PADDLE_TPU_ICI_BYTES_PER_S"

# roofline calibration: env points at a `calibrate()` JSON (or "0" to
# disable); the default path is the checked-in fit produced by
# tools/calibrate_roofline.py.  A fit is only trusted by default when
# its held-in residual is under this bound.
CALIBRATION_ENV = "PADDLE_TPU_ROOFLINE_CALIBRATION"
DEFAULT_CALIBRATION_RESIDUAL_PCT = 15.0

# v5e inter-chip interconnect: 1600 Gbit/s per chip = 200 GB/s — the
# same chip the HBM budget (15.75 GiB) and peak-FLOPs (197 TF bf16)
# defaults are denominated in.
DEFAULT_ICI_BYTES_PER_S = 200e9

# knob lattice defaults (override per-knob via plan_program(knobs={...}))
DEFAULT_BATCH_BUCKETS = (8, 16, 32, 64, 96, 128)
DEFAULT_GRAD_MERGE = (1, 2)
DEFAULT_BUCKET_MB = (32,)
# ZeRO stages searched when a dp_shard degree is on the lattice: 1 =
# optimizer slots, 2 = + sharded gradient accumulation (only distinct
# from 1 under gradient_merge), 3 = + full parameter sharding with JIT
# gathers (distributed/sharding.py)
DEFAULT_ZERO_STAGES = (1, 2, 3)

# the full knob tuple one lattice point carries, in table order.
# tp_degree is a BUILD-VARIANT axis (0 = the base build): candidates
# are whole alternative builds of the transformer blocks via the
# tensor_parallel builders, entering the lattice like the ring knob —
# pre-built pairs in `variants={"tp": {degree: (main, startup)}}`, or
# auto-generated from `model_config=`.
KNOB_KEYS = ("batch", "remat", "dp_shard", "zero_stage", "grad_merge",
             "bucket_mb", "ring", "tp_degree", "scan_hoist")

# gradient reduction collectives XLA overlaps with backward compute —
# on ring 0 (the dp axis) only: an mp-ring collective sits on the
# forward/backward critical path of the very matmuls it completes, so
# tensor-ring bytes are serial no matter the op type
_OVERLAPPABLE = frozenset((
    "c_allreduce_sum", "c_reducescatter", "mp_allreduce_sum",
    "c_elastic_fold",
))


def ici_bytes_per_chip() -> float:
    """Per-chip ICI bandwidth (bytes/s) the wire leg of the roofline
    divides by (``PADDLE_TPU_ICI_BYTES_PER_S`` env; default v5e
    1600 Gbps = 200 GB/s)."""
    raw = os.environ.get(ICI_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_ICI_BYTES_PER_S


# ---------------------------------------------------------------------------
# roofline calibration
# ---------------------------------------------------------------------------
class Calibration:
    """A fitted mapping from the roofline's predicted components to
    wall-clock step time on one host class:

        step_ms = max(compute_ms / eff_compute,
                      wire_overlap_ms / eff_wire_overlap)
                  + wire_serial_ms / eff_wire_serial
                  + overhead_ms

    The three ``eff_*`` coefficients are per-class efficiencies in
    (0, 1] — the fraction of the peak rate that leg actually sustains —
    and ``overhead_ms`` is the per-dispatch constant (tracing epilogue,
    host transfer, runtime launch) the pure roofline prices at zero.
    ``overhead_ms_by_world`` refines the intercept per MESH CLASS
    (world size): a world=8 dispatch pays shard_map splitting and an
    8-way runtime launch a world=1 dispatch never sees, so one shared
    intercept fits whichever class dominates the ladder and misses the
    other (ROADMAP calibration item (b): 46% on fc512_b8).  `step_ms`
    consults it when the caller passes ``world=``; unknown worlds fall
    back to the shared intercept.  A coefficient whose component is
    zero in every fitted pair is unidentifiable and stays at 1.0
    (recorded in ``unidentified``).

    Produced by `calibrate(pairs)`; consumed by `plan_program` (every
    priced candidate's ``step_ms``/``samples_per_sec`` pass through
    `step_ms()` and the record is stamped ``calibrated=True``)."""

    __slots__ = ("eff_compute", "eff_wire_overlap", "eff_wire_serial",
                 "overhead_ms", "overhead_ms_by_world", "residual_pct",
                 "n_pairs", "unidentified", "source")

    def __init__(self, eff_compute: float = 1.0,
                 eff_wire_overlap: float = 1.0,
                 eff_wire_serial: float = 1.0,
                 overhead_ms: float = 0.0,
                 overhead_ms_by_world: Optional[Dict[int, float]] = None,
                 residual_pct: float = 0.0, n_pairs: int = 0,
                 unidentified: Tuple[str, ...] = (),
                 source: str = ""):
        self.eff_compute = float(eff_compute)
        self.eff_wire_overlap = float(eff_wire_overlap)
        self.eff_wire_serial = float(eff_wire_serial)
        self.overhead_ms = float(overhead_ms)
        self.overhead_ms_by_world = {
            int(w): float(v)
            for w, v in (overhead_ms_by_world or {}).items()}
        self.residual_pct = float(residual_pct)
        self.n_pairs = int(n_pairs)
        self.unidentified = tuple(unidentified)
        self.source = str(source)

    def overhead_for(self, world: Optional[int] = None) -> float:
        if world is not None:
            hit = self.overhead_ms_by_world.get(int(world))
            if hit is not None:
                return hit
        return self.overhead_ms

    def step_ms(self, compute_ms: float, wire_overlap_ms: float,
                wire_serial_ms: float,
                world: Optional[int] = None) -> float:
        return (max(compute_ms / self.eff_compute,
                    wire_overlap_ms / self.eff_wire_overlap) +
                wire_serial_ms / self.eff_wire_serial +
                self.overhead_for(world))

    def to_dict(self) -> Dict:
        return {
            "eff_compute": round(self.eff_compute, 6),
            "eff_wire_overlap": round(self.eff_wire_overlap, 6),
            "eff_wire_serial": round(self.eff_wire_serial, 6),
            "overhead_ms": round(self.overhead_ms, 6),
            "overhead_ms_by_world": {
                str(w): round(v, 6)
                for w, v in sorted(self.overhead_ms_by_world.items())},
            "residual_pct": round(self.residual_pct, 4),
            "n_pairs": self.n_pairs,
            "unidentified": list(self.unidentified),
        }

    @classmethod
    def from_dict(cls, d: Dict, source: str = "") -> "Calibration":
        return cls(eff_compute=d.get("eff_compute", 1.0),
                   eff_wire_overlap=d.get("eff_wire_overlap", 1.0),
                   eff_wire_serial=d.get("eff_wire_serial", 1.0),
                   overhead_ms=d.get("overhead_ms", 0.0),
                   overhead_ms_by_world=d.get("overhead_ms_by_world"),
                   residual_pct=d.get("residual_pct", 0.0),
                   n_pairs=d.get("n_pairs", 0),
                   unidentified=tuple(d.get("unidentified") or ()),
                   source=source)

    def save(self, path: str, extra: Optional[Dict] = None):
        import json
        rec = {"calibration": self.to_dict()}
        if extra:
            rec.update(extra)
        with open(path, "w") as f:
            json.dump(rec, f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Calibration":
        import json
        with open(path) as f:
            rec = json.load(f)
        return cls.from_dict(rec.get("calibration") or rec, source=path)

    def __repr__(self):
        return (f"Calibration(eff_compute={self.eff_compute:.3f}, "
                f"eff_wire_overlap={self.eff_wire_overlap:.3f}, "
                f"eff_wire_serial={self.eff_wire_serial:.3f}, "
                f"overhead_ms={self.overhead_ms:.3f}, "
                f"residual_pct={self.residual_pct:.1f}, "
                f"n_pairs={self.n_pairs})")


def calibrate(pairs: List[Dict]) -> Calibration:
    """Fit a `Calibration` from (predicted components, measured) pairs.

    Each pair is a dict with ``compute_ms``, ``wire_overlap_ms``,
    ``wire_serial_ms`` (the planner's per-candidate roofline legs, e.g.
    straight out of a `Plan.trace` record) and ``measured_ms`` (the
    wall-clock per-step time of the SAME candidate on the target host).
    A pair may also carry ``world`` (the mesh size the measurement ran
    on); when two or more world classes are present the dispatch
    intercept is fitted PER CLASS — a world=8 dispatch pays shard_map
    splitting and an 8-way launch a world=1 dispatch never sees, and
    sharing one intercept across both makes whichever class is rarer in
    the ladder fit worst.  The shared ``overhead_ms`` remains the
    pair-weighted mean of the class intercepts, the fallback for worlds
    the ladder never measured.

    The fit is a deterministic coordinate descent minimizing the mean
    squared RELATIVE error (so a 10 ms shape and a 1000 ms shape weigh
    equally), each coordinate refined over a shrinking log/linear grid.
    ``residual_pct`` is the mean absolute percent error of the final
    fit over the fitted pairs — the number the default-on gate
    (`DEFAULT_CALIBRATION_RESIDUAL_PCT`) compares against."""
    pts = [(max(0.0, float(p["compute_ms"])),
            max(0.0, float(p["wire_overlap_ms"])),
            max(0.0, float(p["wire_serial_ms"])),
            float(p["measured_ms"]),
            int(p["world"]) if p.get("world") is not None else None)
           for p in pairs if float(p.get("measured_ms") or 0) > 0]
    if not pts:
        raise ValueError("calibrate: no pairs with measured_ms > 0")

    ident_c = any(c > 0 for c, _, _, _, _ in pts)
    ident_w = any(w > 0 for _, w, _, _, _ in pts)
    ident_s = any(s > 0 for _, _, s, _, _ in pts)

    # one intercept coordinate per world class when ≥2 classes measured;
    # otherwise a single shared "oh" (the pre-per-world behaviour).
    worlds = sorted({wd for *_, wd in pts if wd is not None})
    per_world = len(worlds) >= 2
    oh_keys = ([f"oh@{wd}" for wd in worlds] +
               (["oh"] if any(wd is None for *_, wd in pts) else [])
               ) if per_world else ["oh"]

    def _oh_key(wd):
        return f"oh@{wd}" if per_world and wd is not None else "oh"

    def _err(trial):
        tot = 0.0
        ec, ew, es = trial["ec"], trial["ew"], trial["es"]
        for c, w, s, m, wd in pts:
            pred = max(c / ec, w / ew) + s / es + trial[_oh_key(wd)]
            rel = (pred - m) / m
            tot += rel * rel
        return tot / len(pts)

    # coefficient search windows: efficiencies in (1e-4, 1]; each
    # intercept in [0, min measured in its class] (an intercept above
    # the class's fastest pair would fit negative work).  Shrink rounds
    # of 17-point per-coordinate grids ≈ 1e-3 relative resolution,
    # deterministic and dependency-free.
    coords = {"ec": 0.5 if ident_c else 1.0,
              "ew": 0.5 if ident_w else 1.0,
              "es": 0.5 if ident_s else 1.0}
    spans = {"ec": (1e-4, 1.0), "ew": (1e-4, 1.0), "es": (1e-4, 1.0)}
    for k in oh_keys:
        cls = [m for _, _, _, m, wd in pts if _oh_key(wd) == k]
        coords[k] = 0.0
        spans[k] = (0.0, min(cls) if cls else 0.0)
    active = ([k for k, flag in (("ec", ident_c), ("ew", ident_w),
                                 ("es", ident_s)) if flag] + oh_keys)
    for _round in range(4):
        for key in active:
            lo, hi = spans[key]
            best_v, best_e = coords[key], None
            n = 17
            for i in range(n):
                if key.startswith("oh"):
                    v = lo + (hi - lo) * i / (n - 1) if hi > lo else lo
                else:  # log-spaced: efficiencies vary over decades
                    v = math.exp(math.log(max(lo, 1e-4)) +
                                 (math.log(hi) - math.log(max(lo, 1e-4))) *
                                 i / (n - 1))
                trial = dict(coords)
                trial[key] = v
                e = _err(trial)
                if best_e is None or e < best_e:
                    best_v, best_e = v, e
            coords[key] = best_v
            # shrink the window around the winner for the next round
            width = (hi - lo) / 4
            spans[key] = (max(spans[key][0], best_v - width),
                          min(spans[key][1], best_v + width))

    ec, ew, es = coords["ec"], coords["ew"], coords["es"]
    resid = sum(abs(max(c / ec, w / ew) + s / es + coords[_oh_key(wd)] - m)
                / m for c, w, s, m, wd in pts) / len(pts) * 100.0
    unident = tuple(n for n, flag in (("compute", ident_c),
                                      ("wire_overlap", ident_w),
                                      ("wire_serial", ident_s)) if not flag)
    by_world = ({wd: coords[f"oh@{wd}"] for wd in worlds}
                if per_world else {})
    if per_world:
        # shared fallback intercept = pair-weighted mean of the fitted
        # class intercepts (worlds the ladder never measured get this)
        oh = (sum(coords[_oh_key(wd)] for *_, wd in pts) / len(pts))
    else:
        oh = coords["oh"]
    return Calibration(eff_compute=ec, eff_wire_overlap=ew,
                       eff_wire_serial=es, overhead_ms=oh,
                       overhead_ms_by_world=by_world,
                       residual_pct=resid, n_pairs=len(pts),
                       unidentified=unident)


_CALIB_CACHE: Dict[Tuple, Optional[Calibration]] = {}


def default_calibration() -> Optional[Calibration]:
    """The calibration `plan_program` applies when the caller passes
    none: the file named by ``PADDLE_TPU_ROOFLINE_CALIBRATION`` (unset →
    the checked-in ``perf_r05/roofline_calibration.json``; "0"/"off" →
    disabled), trusted only when its recorded residual is under
    `DEFAULT_CALIBRATION_RESIDUAL_PCT`.  Cached per (path, mtime)."""
    raw = os.environ.get(CALIBRATION_ENV, "")
    if raw.lower() in ("0", "off", "false", "none"):
        return None
    path = raw or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "perf_r05", "roofline_calibration.json")
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    key = (path, mtime)
    if key not in _CALIB_CACHE:
        try:
            calib = Calibration.load(path)
        except Exception:
            calib = None
        if calib is not None and not (
                calib.residual_pct < DEFAULT_CALIBRATION_RESIDUAL_PCT):
            calib = None  # fit exists but isn't trusted yet
        _CALIB_CACHE.clear()  # one live entry; stale mtimes never pile up
        _CALIB_CACHE[key] = calib
    return _CALIB_CACHE[key]


class Plan:
    """The argmax of one `plan_program` search.

    ``knobs``: {"batch", "remat", "dp_shard", "zero_stage", "grad_merge",
    "bucket_mb", "ring"} — the applied spelling of the lattice point.
    ``predicted`` fields are the roofline numbers for the chosen
    candidate; ``trace`` is the full per-candidate table (one dict per
    lattice point, priced and gated — the docs/perf.md decision-table
    source)."""

    def __init__(self, knobs: Dict, world: int, hbm_budget_bytes: int,
                 chosen: Dict, trace: List[Dict]):
        self.knobs = dict(knobs)
        self.world = int(world)
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.trace = list(trace)
        self.predicted_step_ms = float(chosen["step_ms"])
        self.predicted_samples_per_sec = float(chosen["samples_per_sec"])
        self.predicted_peak_bytes = int(chosen["peak_bytes"])
        self.predicted_fits = bool(chosen["fits"])
        self.predicted_wire_bytes = int(chosen["wire_bytes"])
        self.predicted_wire_bytes_per_axis = dict(
            chosen.get("wire_bytes_per_axis") or {})
        self.predicted_compute_ms = float(chosen["compute_ms"])
        self.predicted_wire_ms = float(chosen["wire_overlap_ms"] +
                                       chosen["wire_serial_ms"])
        self.predicted_flops = int(chosen["flops"])
        self.predicted_effective_global_batch = int(
            chosen.get("effective_global_batch") or 0)
        self.predicted_calibrated = bool(chosen.get("calibrated"))
        # the Calibration the prices passed through (plan_program fills
        # this in; None = raw roofline ranking numbers)
        self.calibration: Optional[Calibration] = None
        # tp build pairs (plan_program fills this in): {degree: (main,
        # startup[, loss_name])} so callers can train the winning build
        self.build_variants: Dict[int, Tuple] = {}

    @property
    def batch(self) -> int:
        return int(self.knobs["batch"])

    def to_dict(self) -> Dict:
        return {
            "knobs": dict(self.knobs),
            "world": self.world,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "predicted_step_ms": round(self.predicted_step_ms, 4),
            "predicted_samples_per_sec":
                round(self.predicted_samples_per_sec, 2),
            "predicted_peak_bytes": self.predicted_peak_bytes,
            "predicted_fits": self.predicted_fits,
            "predicted_wire_bytes": self.predicted_wire_bytes,
            "predicted_wire_bytes_per_axis":
                dict(self.predicted_wire_bytes_per_axis),
            "predicted_compute_ms": round(self.predicted_compute_ms, 4),
            "predicted_wire_ms": round(self.predicted_wire_ms, 4),
            "predicted_effective_global_batch":
                self.predicted_effective_global_batch,
            "calibrated": self.predicted_calibrated,
            "calibration_residual_pct":
                (round(self.calibration.residual_pct, 4)
                 if self.calibration is not None else None),
            "n_candidates": len(self.trace),
        }

    def render_table(self) -> str:
        """The per-candidate trace as a markdown table (the docs/perf.md
        decision-table source)."""
        head = ("| batch | remat | dp_shard | stage | gm K | bucket MB | "
                "ring | tp | scan | peak GiB | fits | step ms | verdict |")
        sep = "|---|---|---|---|---|---|---|---|---|---|---|---|---|"
        rows = [head, sep]
        for c in self.trace:
            rows.append(
                "| {batch} | {remat} | {dp_shard} | {zero_stage} | "
                "{grad_merge} | {bucket_mb} | {ring} | {tp_degree} | "
                "{scan_hoist} | "
                "{gib:.2f} | {fits} | {step_ms:.2f} | {verdict} |".format(
                    gib=c["peak_bytes"] / 2 ** 30,
                    fits="yes" if c["fits"] else "no",
                    **{k: c.get(k, 0)
                       for k in ("batch", "remat", "dp_shard",
                                 "zero_stage", "grad_merge",
                                 "bucket_mb", "ring", "tp_degree",
                                 "scan_hoist", "step_ms", "verdict")}))
        return "\n".join(rows)

    def __repr__(self):
        return (f"Plan(knobs={self.knobs}, world={self.world}, "
                f"step_ms={self.predicted_step_ms:.2f}, "
                f"fits={self.predicted_fits})")


class _QuietVerify:
    """Disable the env-gated per-pass self-checks while the planner
    applies CANDIDATE rewrites: the planner gates every surviving
    candidate through `check_program(level="collective")` itself, so a
    second full verification inside every rewrite of every lattice point
    would only multiply the search cost.  `apply_plan` (the real
    application) keeps the self-checks armed."""

    def __enter__(self):
        from .verifier import VERIFY_ENV
        self._prev = os.environ.get(VERIFY_ENV)
        if self._prev:
            os.environ[VERIFY_ENV] = ""
        return self

    def __exit__(self, *exc):
        from .verifier import VERIFY_ENV
        if self._prev is not None:
            os.environ[VERIFY_ENV] = self._prev
        return False


def _knob_lattice(world: int, batch: Optional[int], knobs: Optional[Dict],
                  have_ring_variant: bool,
                  can_remat: bool, can_gm: bool,
                  tp_candidates: Tuple[int, ...] = ()) -> List[Dict]:
    """Enumerate the candidate lattice points (dicts of knob values),
    deduplicating no-op combinations (bucket_mb only matters when
    sharding; remat only when checkpoints exist; gm only when the
    program recorded its param/grad pairs).  `tp_candidates` are the
    tensor-parallel degrees build variants exist for; each tp degree
    carves the world into dp×tp, so the dp_shard axis under tp `d`
    ranges over divisors of world//d."""
    knobs = dict(knobs or {})
    batches = tuple(knobs.get("batch") or
                    ((int(batch),) if batch else DEFAULT_BATCH_BUCKETS))
    remats = tuple(knobs.get("remat") or
                   ((False, True) if can_remat else (False,)))
    stages = tuple(knobs.get("zero_stage") or DEFAULT_ZERO_STAGES)
    gms = tuple(knobs.get("grad_merge") or
                (DEFAULT_GRAD_MERGE if can_gm else (1,)))
    buckets = tuple(knobs.get("bucket_mb") or DEFAULT_BUCKET_MB)
    rings = tuple(knobs.get("ring") or
                  ((False, True) if have_ring_variant else (False,)))
    # scan_hoist is a DISPATCH knob, not a rewrite: it rides any
    # gradient-merge candidate (the hoisted window needs a commit tail
    # to hoist) and shares the gm candidate's rewrite point
    hoists = tuple(knobs.get("scan_hoist") or
                   ((False, True) if can_gm else (False,)))
    tps = tuple(knobs.get("tp_degree")
                if knobs.get("tp_degree") is not None
                else ((0,) + tuple(sorted(tp_candidates))))

    seen = set()
    out = []
    for tp in tps:
        tp = int(tp)
        if tp > 1 and tp not in tp_candidates:
            continue  # no build variant for this degree
        if tp > 1 and world % tp != 0:
            continue
        dp_world = world // tp if tp > 1 else world
        dps_raw = knobs.get("dp_shard") or \
            ((0, dp_world) if dp_world > 1 else (0,))
        # under tp the dp sub-axis shrinks: a requested shard degree
        # that no longer divides it is dropped, not mis-padded
        dps = tuple(d for d in dps_raw
                    if d == 0 or (d <= dp_world and dp_world % d == 0)) \
            or (0,)
        for b, r, dp, z, gm, mb, ring, sh in itertools.product(
                batches, remats, dps, stages, gms, buckets, rings, hoists):
            if ring and not have_ring_variant:
                continue
            if ring and tp > 1:
                continue  # one model axis per mesh (ring = sp)
            if not can_remat and r and tp == 0:
                continue
            if not can_gm and gm > 1 and tp == 0:
                continue
            mb_eff = int(mb) if dp > 1 else 0  # bucket size is a ZeRO knob
            # the stage axis only exists once a dp degree does; stage 2
            # without gradient_merge IS stage 1 (the sharded accumulator
            # only materializes under a merge window), so it collapses
            z_eff = int(z) if dp > 1 else 0
            if z_eff == 2 and gm <= 1:
                z_eff = 1
            # the hoist needs a commit tail: no merge window, nothing
            # to hoist — the knob collapses to the looped dispatch
            sh_eff = bool(sh) and int(gm) > 1
            key = (int(b), bool(r), int(dp), z_eff, int(gm), mb_eff,
                   bool(ring), tp, sh_eff)
            if key in seen:
                continue
            seen.add(key)
            out.append({"batch": int(b), "remat": bool(r),
                        "dp_shard": int(dp), "zero_stage": z_eff,
                        "grad_merge": int(gm), "bucket_mb": mb_eff,
                        "ring": bool(ring), "tp_degree": tp,
                        "scan_hoist": sh_eff})
    return out


def _apply_knobs(main: Program, startup: Optional[Program],
                 cand: Dict) -> Tuple[Program, Optional[Program]]:
    """Apply one lattice point as REAL rewrites on clones of
    (main, startup) and return the rewritten pair.  Order matters:
    remat touches fwd/bwd only, sharding rewrites the optimizer tail,
    gradient_merge must come after sharding (verifier V502).  Knobs the
    base program already carries (pinned lattice points) are skipped —
    the clone inherits the applied-passes registry, and each guard
    below mirrors `apply_plan`'s."""
    from ..core.pass_framework import has_applied
    from ..core.program import Program as _P
    m = main.clone()
    s = startup.clone() if startup is not None else _P()
    if cand["remat"] and not has_applied(m, "recompute"):
        from .recompute_rewrite import apply_recompute
        apply_recompute(m)
    if cand["dp_shard"] > 1 and not has_applied(m, "zero1_sharding"):
        from ..distributed.sharding import shard_optimizer_states
        shard_optimizer_states(
            m, s, dp_degree=cand["dp_shard"],
            bucket_bytes=(cand["bucket_mb"] * 2 ** 20
                          if cand["bucket_mb"] else None),
            stage=int(cand.get("zero_stage") or 1))
    if cand["grad_merge"] > 1 and not has_applied(m, "gradient_merge"):
        from .optimizer import gradient_merge
        gradient_merge(m, cand["grad_merge"], s)
    return m, s


class _RewritePoint:
    """One (remat, dp_shard, grad_merge, bucket_mb, ring, tp_degree)
    rewrite tuple, applied and wire-priced ONCE and shared by every
    batch bucket — batch is a feed-time binding, not a rewrite, so
    re-cloning and re-verifying per batch would multiply the dominant
    cost by the bucket count for byte-identical IR.  Wire bytes are kept
    as (fixed, per-batch-unit) pairs: weight-shaped collectives price
    once, activation collectives (the mp ring's whole traffic — partial
    sums and the f-operator's backward psum ride [-1, ...] operands)
    scale with the batch bucket at `_price` time."""

    __slots__ = ("main", "startup", "reduced", "tp", "dp_world",
                 "wire_overlap", "wire_serial", "wire_by_axis",
                 "wire_publish", "wire_publish_by_axis",
                 "mp_sharded", "error", "verify_verdict", "price_cache")

    def __init__(self, base_main, base_startup, cand, world):
        from .verifier import (collective_sequence, entry_wire_bytes,
                               _ring_degrees_from_seq, ring_axis)
        self.error = None
        self.verify_verdict = None  # lazily computed, cached
        # (peak_bytes, mem_fits, flops) per batch bucket: the HBM and
        # FLOPs walks are scan_hoist-independent, so the hoisted and
        # looped spellings of one rewrite point share them
        self.price_cache: Dict[int, Tuple[int, bool, int]] = {}
        self.tp = int(cand.get("tp_degree") or 0)
        self.dp_world = world // self.tp if self.tp > 1 else world
        # (fixed, per-batch-unit) accumulators
        self.wire_overlap = [0.0, 0.0]
        self.wire_serial = [0.0, 0.0]
        self.wire_by_axis: Dict[str, List[float]] = {}
        # publish-role bytes tracked SEPARATELY (a subset of the serial
        # bucket): the scan_hoist knob prices them at 1/K because the
        # hoisted commit tail publishes once per merge window
        self.wire_publish = [0.0, 0.0]
        self.wire_publish_by_axis: Dict[str, List[float]] = {}
        self.mp_sharded = None
        try:
            self.main, self.startup = _apply_knobs(base_main, base_startup,
                                                   cand)
        except Exception as e:  # a refused composition is a verdict
            self.main = self.startup = self.reduced = None
            self.error = e
            return
        if self.tp > 1:
            # batch-independent: computed once here, shared by every
            # batch bucket's HBM walk instead of re-running propagation
            from .memory_analysis import mp_sharded_vars
            self.mp_sharded = mp_sharded_vars(self.main, self.tp)
        self.reduced = self.main
        if self.dp_world > 1:
            from ..distributed.compiled_program import insert_grad_allreduce
            self.reduced = insert_grad_allreduce(self.main)
        if self.dp_world > 1 or self.tp > 1:
            # each ring priced at its OWN degree (a tensor-parallel
            # collective on a dp×tp candidate moves mp-ring bytes, not
            # dp-world bytes) — the stamps are the authority; one
            # sequence extraction serves both the degrees and the walk.
            # Ring 0's fallback degree is the DP SUB-world: on a 4×2
            # candidate the grad allreduce crosses 4 ranks, not 8.
            seq = collective_sequence(self.reduced)
            ring_degrees = _ring_degrees_from_seq(seq)
            for e in seq:
                fixed = entry_wire_bytes(e, self.dp_world, ring_degrees)
                per_unit = entry_wire_bytes(e, self.dp_world, ring_degrees,
                                            batch=1) - fixed
                # XLA overlaps dp-ring gradient reductions with backward
                # compute; mp-ring collectives sit on the critical path
                # of the matmuls they complete, so they price serial
                bucket = (self.wire_overlap
                          if e["type"] in _OVERLAPPABLE
                          and e["ring_id"] == 0 else self.wire_serial)
                bucket[0] += fixed
                bucket[1] += per_unit
                axis = ring_axis(e["ring_id"], e.get("mp_axis"))
                ax = self.wire_by_axis.setdefault(axis, [0.0, 0.0])
                ax[0] += fixed
                ax[1] += per_unit
                if e.get("zero_role") == "publish":
                    self.wire_publish[0] += fixed
                    self.wire_publish[1] += per_unit
                    pa = self.wire_publish_by_axis.setdefault(
                        axis, [0.0, 0.0])
                    pa[0] += fixed
                    pa[1] += per_unit

    def verify(self) -> str:
        """check_program on the reduced program — once per rewrite point
        (the verdict is batch-independent).  1-D candidates gate at
        level "collective"; 2-D (tp) candidates gate the full layout
        analyzer too (level "layout", V601-V605) so the search space
        never contains a mis-reduced layout."""
        if self.verify_verdict is None:
            from .verifier import check_program
            level = "layout" if self.tp > 1 else "collective"
            report = check_program(self.reduced, level=level,
                                   startup=self.startup)
            if report.errors:
                self.verify_verdict = "dropped: " + ",".join(
                    sorted({d.code for d in report.errors}))
            else:
                self.verify_verdict = "verified"
        return self.verify_verdict


def _price(point: _RewritePoint, cand: Dict, hbm_budget: Optional[int],
           peak_flops: float, ici_bps: float, world: int,
           global_batch: Optional[int] = None,
           calib: Optional[Calibration] = None) -> Dict:
    """Roofline-price one (rewrite point, batch) candidate.

    2-D accounting: compute divides the mp-STAMPED ops' walked FLOPs by
    the tp degree (the Megatron col/row matmuls and their grads carry
    the builders' ``mp_axis`` stamp, which autodiff copies onto the grad
    ops; the attention core's per-head work is already walked at its
    local shard shapes), the HBM walker charges 1/tp of mp-sharded
    param/activation bytes (`analyze_program(tp_degree=)`), and wire
    combines each ring's fixed and batch-proportional legs.  The
    objective stays samples/sec/CHIP: a tp candidate's batch feeds
    world/tp data-parallel replicas, so its per-chip rate is
    batch·dp_world/world per step — pure-dp candidates reduce to the
    classic batch/step.

    `global_batch` is the effective-global-batch constraint: a
    candidate whose batch × dp replicas × grad-merge window falls short
    of the demanded global batch is infeasible no matter how fast."""
    from .memory_analysis import analyze_program
    from .flops_analysis import analyze_flops

    batch = cand["batch"]
    tp = point.tp
    cached = point.price_cache.get(batch)
    if cached is None:
        mem = analyze_program(point.main, batch=batch,
                              budget_bytes=hbm_budget,
                              tp_degree=tp if tp > 1 else None,
                              tp_sharded=point.mp_sharded)
        rep = analyze_flops(point.main, batch=batch)
        flops = rep["total_flops"]
        if tp > 1:
            block = point.main.global_block()
            sharded = sum(
                r["flops"] for r in rep["per_op"]
                if block.ops[r["index"]].attrs.get("mp_axis"))
            flops = (flops - sharded) + sharded / tp
        cached = (int(mem["peak_bytes"]), bool(mem["fits"]), flops)
        point.price_cache[batch] = cached
    peak_bytes, mem_fits, flops = cached
    compute_s = flops / peak_flops if peak_flops else 0.0
    wo = point.wire_overlap[0] + batch * point.wire_overlap[1]
    ws = point.wire_serial[0] + batch * point.wire_serial[1]
    gm_k = max(1, int(cand["grad_merge"]))
    axis_discount: Dict[str, float] = {}
    if cand.get("scan_hoist") and gm_k > 1:
        # hoisted commit tail: the publish allgather runs once per
        # K-step window, so its per-step bytes price at 1/K (publish is
        # always serial — allgather after the sharded update)
        pub = point.wire_publish[0] + batch * point.wire_publish[1]
        ws -= pub * (1.0 - 1.0 / gm_k)
        axis_discount = {
            a: (f + batch * u) * (1.0 - 1.0 / gm_k)
            for a, (f, u) in point.wire_publish_by_axis.items()}
    wo_s = wo / ici_bps if ici_bps else 0.0
    ws_s = ws / ici_bps if ici_bps else 0.0
    if calib is not None:
        step_s = calib.step_ms(compute_s * 1e3, wo_s * 1e3,
                               ws_s * 1e3, world=int(world)) / 1e3
    else:
        step_s = max(compute_s, wo_s) + ws_s
    eff_batch = batch * point.dp_world * gm_k
    rec = dict(cand)
    rec.update({
        "peak_bytes": peak_bytes,
        "fits": mem_fits,
        "flops": int(flops),
        "wire_bytes": int(wo + ws),
        "wire_bytes_per_axis": {
            a: int(f + batch * u - axis_discount.get(a, 0.0))
            for a, (f, u) in sorted(point.wire_by_axis.items())},
        "compute_ms": compute_s * 1e3,
        "wire_overlap_ms": wo_s * 1e3,
        "wire_serial_ms": ws_s * 1e3,
        "step_ms": step_s * 1e3,
        "calibrated": calib is not None,
        "effective_global_batch": int(eff_batch),
        "samples_per_sec": (batch * point.dp_world / max(1, world) / step_s)
        if step_s > 0 else 0.0,
        "verdict": "",
    })
    if global_batch and eff_batch < int(global_batch):
        rec["fits"] = False
        rec["verdict"] = (f"under global batch "
                          f"({eff_batch} < {int(global_batch)})")
    return rec


def _tp_variants_from_config(model_config: Dict, world: int,
                             degrees=None) -> Dict[int, Tuple]:
    """Auto-generate tensor-parallel BUILD variants from a model config:
    each candidate degree rebuilds the transformer blocks through the
    `tensor_parallel` builders (`models.build_transformer_lm` with
    ``tensor_parallel_degree=``) and minimizes the same optimizer, so
    the planner can search tp without the caller hand-feeding the
    winner.  Config keys: ``vocab_size``, ``hidden``, ``num_layers``,
    ``num_heads``, ``seq_len``; optional ``learning_rate`` (default
    1e-3) and ``optimizer`` ("adam" | "sgd", default "adam").  Candidate
    degrees (when not given): powers of two ≥ 2 dividing the world,
    the head count and the hidden width.  Returns {degree: (main,
    startup, loss_name)}."""
    import paddle_tpu.static as static
    from ..models.static_lm import build_transformer_lm
    cfg = dict(model_config)
    heads = int(cfg["num_heads"])
    hidden = int(cfg["hidden"])
    if degrees is None:
        degrees, d = [], 2
        while d <= min(int(world), heads):
            if world % d == 0 and heads % d == 0 and hidden % d == 0:
                degrees.append(d)
            d *= 2
    out: Dict[int, Tuple] = {}
    lr = float(cfg.get("learning_rate", 1e-3))
    opt_name = str(cfg.get("optimizer", "adam")).lower()
    for d in degrees:
        d = int(d)
        if d < 2:
            continue
        main, startup, loss, _ = build_transformer_lm(
            vocab_size=int(cfg["vocab_size"]), hidden=hidden,
            num_layers=int(cfg["num_layers"]), num_heads=heads,
            seq_len=int(cfg["seq_len"]), tensor_parallel_degree=d)
        with static.program_guard(main, startup):
            opt = (static.SGD(learning_rate=lr) if opt_name == "sgd"
                   else static.Adam(learning_rate=lr))
            opt.minimize(loss)
        out[d] = (main, startup, loss.name)
    return out


def _built_tp_degree(program: Program) -> int:
    """The tp degree a program was BUILT with (0 for plain builds) —
    the shared registry rule (`core.pass_framework.built_tp_degree`),
    so the planner's pinning and the verifier's V504 drift check can
    never disagree."""
    from ..core.pass_framework import built_tp_degree
    return built_tp_degree(program)


def plan_program(program: Program, startup: Optional[Program] = None,
                 world: int = 1, hbm_budget: Optional[int] = None,
                 knobs: Optional[Dict] = None, batch: Optional[int] = None,
                 variants: Optional[Dict[str, Tuple[Program,
                                                    Program]]] = None,
                 model_config: Optional[Dict] = None,
                 global_batch: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 ici_bytes_per_s: Optional[float] = None,
                 verify: bool = True,
                 calibration: Optional[Calibration] = None) -> Plan:
    """Compile-time search for the best training configuration of
    `program` on a `world`-chip mesh (data-parallel, or 2-D dp×tp when
    tensor-parallel build variants are in the lattice).  Returns a
    `Plan`.

    * `program`/`startup` — a minimized (optimizer ops appended)
      training program pair.  Neither is modified: every candidate is
      applied to clones; call `apply_plan` (or `bench.py --auto`) to
      apply the winner for real.
    * `world` — total chip count the wire costs and shard candidates
      target (1 = single chip, no wire).  A tp-degree-`d` candidate
      carves it into a (world/d) × d dp×tp mesh.
    * `hbm_budget` — per-chip budget bytes for the fits gate (default
      `PADDLE_TPU_HBM_BYTES` → v5e usable 15.75 GiB).
    * `knobs` — per-knob candidate overrides, e.g. ``{"batch": (64, 96),
      "grad_merge": (1,)}``; unset knobs use the default lattice.
    * `batch` — pin the batch bucket (equivalent to
      ``knobs={"batch": (b,)}``).  Under tp this is the per-dp-replica
      batch (all tp shards of a replica consume the same rows).
    * `variants` — alternative BUILDS of the same model keyed by knob:
      ``{"ring": (main, startup)}`` for ring attention, and
      ``{"tp": {degree: (main, startup)}}`` for Megatron tensor
      parallelism — tp is emitted at build time by the
      `distributed/tensor_parallel` builders, so each searched degree
      enters the lattice as a pre-built pair like the ring knob.
    * `model_config` — auto-generate the tp variants instead of
      hand-feeding them: a dict of `models.build_transformer_lm`
      geometry (``vocab_size``/``hidden``/``num_layers``/``num_heads``/
      ``seq_len`` + optional ``learning_rate``/``optimizer``); the
      planner rebuilds the blocks through the tensor_parallel builders
      for every viable power-of-two degree.  The generated pairs ride
      ``plan.build_variants`` so the caller can train the winner.
    * `global_batch` — the effective-global-batch constraint: every
      candidate must reach ``batch × dp_replicas × grad_merge ≥
      global_batch`` or it is infeasible — this is how gradient-merge ×
      tp candidates WIN when the user demands a batch no single-chip
      plan can hold, instead of the search returning
      ``predicted_fits=False``.
    * `peak_flops` / `ici_bytes_per_s` — roofline denominators (default:
      the v5e targets via `peak_flops_per_chip("tpu")` and
      `ici_bytes_per_chip()`; planning always prices the TPU target even
      when the planner itself runs on a CPU host).
    * `verify` — gate every HBM-feasible candidate through
      `check_program` and drop any with error diagnostics: level
      "collective" for 1-D candidates, level "layout" (the V6xx
      sharding-propagation analyzer) for every 2-D tp candidate — the
      search space never contains a deadlocking or mis-reduced plan.
      Leave on; it exists as a switch only for estimator-sweep modes
      that re-plan the same program family many times
      (`bench.py --seq-ladder`).
    * `calibration` — a `Calibration` every candidate's price passes
      through (``calibrated=True`` in the trace records).  Default
      (None) consults `default_calibration()`: the checked-in
      ``perf_r05/roofline_calibration.json`` fit when its residual is
      under `DEFAULT_CALIBRATION_RESIDUAL_PCT` (env
      ``PADDLE_TPU_ROOFLINE_CALIBRATION`` overrides the path or
      disables with "0").  Pass ``False`` to force raw roofline
      ranking numbers.

    Selection: among verified fitting candidates, maximize predicted
    samples/sec/chip (ties prefer fewer knobs, then lower peak bytes).
    If NOTHING fits, the minimum-peak candidate is returned with
    ``predicted_fits=False`` — callers (seq-ladder, bench) surface that
    verdict instead of executing.

    The search cost is estimator-cheap by construction: every candidate
    is clone + rewrite + three IR walks — no compilation, no device.
    """
    from .flops_analysis import peak_flops_per_chip
    from .memory_analysis import hbm_budget_bytes
    from ..core.pass_framework import applied_passes, has_applied

    world = max(1, int(world))
    budget = int(hbm_budget) if hbm_budget else hbm_budget_bytes()
    peak = float(peak_flops) if peak_flops else peak_flops_per_chip("tpu")
    ici = float(ici_bytes_per_s) if ici_bytes_per_s else ici_bytes_per_chip()
    calib = default_calibration() if calibration is None else \
        (calibration or None)
    variants = dict(variants or {})

    # tensor-parallel build variants: hand-fed pairs win; a model config
    # auto-generates the rest (only degrees not already supplied)
    tp_builds: Dict[int, Tuple] = {
        int(d): tuple(pair) for d, pair in (variants.get("tp") or {}).items()
        if int(d) > 1}
    if model_config is not None:
        want = None
        if knobs and knobs.get("tp_degree") is not None:
            want = [int(d) for d in knobs["tp_degree"] if int(d) > 1]
        generated = _tp_variants_from_config(model_config, world,
                                             degrees=want)
        for d, triple in generated.items():
            tp_builds.setdefault(d, triple)

    from .memory_analysis import select_layer_checkpoints
    can_remat = (has_applied(program, "recompute") or
                 bool(select_layer_checkpoints(program)))
    # knobs already burned into the input program are PINNED, not
    # re-searched: a pre-rematerialized program can't un-remat, a
    # pre-sharded one can't unshard, a pre-merged one can't un-merge,
    # and a ring-built program can't drop its ring op — the lattice
    # must describe clones that can actually exist, and the recorded
    # plan must match the applied state (V504)
    pre_remat = has_applied(program, "recompute")
    pre_dp = pre_bucket_mb = pre_stage = 0
    if has_applied(program, "zero1_sharding"):
        zs = next((e for e in reversed(applied_passes(program))
                   if e["pass"] == "zero1_sharding"), {})
        zplan = getattr(program, "_zero_shard_plan", None)
        pre_dp = int(zplan.dp_degree) if zplan is not None else world
        pre_stage = int(getattr(zplan, "stage", 0) or
                        zs.get("stage", 1)) if zplan is not None else \
            int(zs.get("stage", 1))
        if zs.get("bucket_bytes"):
            pre_bucket_mb = max(1, int(zs["bucket_bytes"]) // 2 ** 20)
    pre_gm = 0
    if has_applied(program, "gradient_merge"):
        gm_meta = getattr(program, "_gm_meta", None) or {}
        pre_gm = int(gm_meta.get("k", 0)) or 1
    pre_ring = any(op.type == "ring_attention"
                   for b in program.blocks for op in b.ops)
    can_gm = bool(getattr(program, "_ps_params_grads", None)) or pre_gm > 0
    # a program BUILT through the tensor_parallel builders can't drop
    # its Megatron collectives — the tp axis pins like the ring knob
    pre_tp = _built_tp_degree(program)
    pre_hoist = has_applied(program, "scan_hoist")

    eff_knobs = dict(knobs or {})
    if pre_hoist:
        eff_knobs["scan_hoist"] = (True,)
    if pre_remat:
        eff_knobs["remat"] = (True,)
    if pre_gm:
        eff_knobs["grad_merge"] = (pre_gm,)
    if pre_ring:
        eff_knobs["ring"] = (True,)
    if pre_tp:
        eff_knobs["tp_degree"] = (pre_tp,)
        tp_builds[pre_tp] = (program, startup)
    if pre_dp:
        # pin through the axis (NOT a post-filter: a pre-sharded degree
        # outside the default (0, world) axis would otherwise empty the
        # lattice and silently discard the batch search)
        eff_knobs["dp_shard"] = (pre_dp,)
        eff_knobs["zero_stage"] = (pre_stage or 1,)
        if pre_bucket_mb:
            eff_knobs["bucket_mb"] = (pre_bucket_mb,)
    tp_candidates = tuple(sorted(tp_builds))
    lattice = _knob_lattice(world, batch, eff_knobs,
                            pre_ring or "ring" in variants,
                            can_remat, can_gm, tp_candidates)
    if not lattice:
        # over-constrained knob lists (e.g. remat forced on a model with
        # no checkpointable layers): fall back to pricing the program
        # as-is so the caller still gets a verdict
        lattice = [{"batch": int(batch or 1), "remat": pre_remat,
                    "dp_shard": pre_dp, "zero_stage": pre_stage,
                    "grad_merge": pre_gm or 1,
                    "bucket_mb": pre_bucket_mb, "ring": pre_ring,
                    "tp_degree": pre_tp, "scan_hoist": bool(pre_hoist)}]

    trace: List[Dict] = []
    points: Dict[Tuple, _RewritePoint] = {}
    with _QuietVerify():
        for cand in lattice:
            base_main, base_startup = (program, startup)
            if cand["ring"] and not pre_ring:
                base_main, base_startup = variants["ring"]
            tp = int(cand.get("tp_degree") or 0)
            if tp > 1 and tp != pre_tp:
                pair = tp_builds[tp]
                base_main, base_startup = pair[0], pair[1]
            rkey = (cand["remat"], cand["dp_shard"], cand["zero_stage"],
                    cand["grad_merge"], cand["bucket_mb"], cand["ring"],
                    tp)
            point = points.get(rkey)
            if point is None:
                point = points[rkey] = _RewritePoint(
                    base_main, base_startup, cand, world)
            if point.error is not None:
                rec = dict(cand)
                rec.update({"peak_bytes": 0, "fits": False, "flops": 0,
                            "wire_bytes": 0, "wire_bytes_per_axis": {},
                            "compute_ms": 0.0,
                            "wire_overlap_ms": 0.0, "wire_serial_ms": 0.0,
                            "step_ms": float("inf"), "samples_per_sec": 0.0,
                            "effective_global_batch": 0,
                            "calibrated": False,
                            "verdict": f"rewrite refused: {point.error!r}"})
                trace.append(rec)
                continue
            rec = _price(point, cand, budget, peak, ici, world,
                         global_batch, calib)
            if verify and rec["fits"]:
                verdict = point.verify()
                rec["verdict"] = verdict
                if verdict != "verified":
                    rec["fits"] = False
            elif rec["fits"]:
                rec["verdict"] = "unverified"
            elif not rec["verdict"]:
                rec["verdict"] = "over budget"
            trace.append(rec)

    feasible = [r for r in trace if r["fits"]]

    def _n_knobs(r):
        # higher ZeRO stages count as extra knobs so ties prefer the
        # least-invasive rewrite (plain < zero1 < zero2 < zero3); a tp
        # build variant counts like any other knob
        return (int(r["remat"]) + int(r["dp_shard"] > 1) +
                max(0, int(r.get("zero_stage") or 0) - 1) +
                int(r["grad_merge"] > 1) + int(r["ring"]) +
                int((r.get("tp_degree") or 0) > 1) +
                int(bool(r.get("scan_hoist"))))

    if feasible:
        chosen = max(feasible,
                     key=lambda r: (r["samples_per_sec"], -_n_knobs(r),
                                    -r["peak_bytes"]))
        chosen = dict(chosen)
        chosen["verdict"] = (chosen["verdict"] + "; chosen").lstrip("; ")
    else:
        # nothing fits: return the least-infeasible point so callers can
        # report HOW far over budget the shape is (seq-ladder rungs)
        pool = [r for r in trace if r["peak_bytes"] > 0] or trace
        chosen = dict(min(pool, key=lambda r: r["peak_bytes"]))
        chosen["verdict"] = (chosen["verdict"] +
                             "; chosen (nothing fits)").lstrip("; ")
    for r in trace:
        if all(r[k] == chosen[k] for k in KNOB_KEYS):
            r["verdict"] = chosen["verdict"]
    knob_dict = {k: chosen[k] for k in KNOB_KEYS}
    plan = Plan(knob_dict, world, budget, chosen, trace)
    plan.calibration = calib
    # the tp build pairs (hand-fed AND auto-generated) ride the plan so
    # a caller can apply/train the winning variant without rebuilding:
    # {degree: (main, startup)} or (main, startup, loss_name) for
    # config-generated builds
    plan.build_variants = dict(tp_builds)
    # non-registry attachment for inspection/telemetry; the REGISTRY
    # entry is written by apply_plan, at application time, so the V504
    # drift check compares a recorded plan only against a program the
    # plan was actually applied to
    program._auto_plan = plan.to_dict()
    return plan


def apply_plan(program: Program, startup: Optional[Program], plan) -> Program:
    """Apply a `Plan` (or its ``knobs`` dict) to the REAL program pair,
    recording the plan in the applied-passes registry so the verifier's
    V504 drift check can flag later hand-edits.  Rewrites run with the
    env-gated self-checks armed (unlike candidate enumeration).

    The ring and tp knobs cannot be applied post-hoc — both are emitted
    at build time — so ``plan.knobs["ring"]=True`` demands the caller
    pass the ring-built program, and ``plan.knobs["tp_degree"]=d``
    demands the degree-`d` tensor-parallel build (``plan.build_variants
    [d]`` when the planner generated it; raises otherwise).  Batch is a
    feed-time binding, not a rewrite; read it from
    ``plan.knobs["batch"]``.
    """
    from ..core.pass_framework import has_applied
    knobs = plan.knobs if isinstance(plan, Plan) else dict(plan)
    has_ring = any(op.type == "ring_attention"
                   for b in program.blocks for op in b.ops)
    if bool(knobs.get("ring")) != has_ring:
        raise ValueError(
            f"apply_plan: plan says ring={bool(knobs.get('ring'))} but the "
            f"program was built with ring_attention={has_ring} — apply the "
            f"plan to the matching build variant "
            f"(nets.scaled_dot_product_attention(sequence_parallel=...))")
    built_tp = _built_tp_degree(program)
    plan_tp = int(knobs.get("tp_degree") or 0)
    if plan_tp != built_tp:
        raise ValueError(
            f"apply_plan: plan says tp_degree={plan_tp} but the program "
            f"was built with tp_degree={built_tp} — apply the plan to "
            f"the matching tensor-parallel build variant "
            f"(plan.build_variants[{plan_tp}], or rebuild through the "
            f"tensor_parallel builders)")
    meta = {k: knobs.get(k) for k in KNOB_KEYS}
    if isinstance(plan, Plan):
        meta["predicted_step_ms"] = round(plan.predicted_step_ms, 4)
        meta["predicted_peak_bytes"] = plan.predicted_peak_bytes
        meta["world"] = plan.world
    if knobs.get("remat") and not has_applied(program, "recompute"):
        from .recompute_rewrite import apply_recompute
        apply_recompute(program)
    if int(knobs.get("dp_shard") or 0) > 1 and \
            not has_applied(program, "zero1_sharding"):
        from ..distributed.sharding import shard_optimizer_states
        shard_optimizer_states(
            program, startup, dp_degree=int(knobs["dp_shard"]),
            bucket_bytes=(int(knobs["bucket_mb"]) * 2 ** 20
                          if knobs.get("bucket_mb") else None),
            stage=int(knobs.get("zero_stage") or 1))
    if int(knobs.get("grad_merge") or 1) > 1 and \
            not has_applied(program, "gradient_merge"):
        from .optimizer import gradient_merge
        gradient_merge(program, int(knobs["grad_merge"]), startup)
    if knobs.get("scan_hoist") and not has_applied(program, "scan_hoist"):
        # dispatch-level knob: validates the window splits cleanly and
        # records it so run_steps' hoisted path + V504 see the intent
        from ..distributed.scan_window import mark_scan_hoist
        mark_scan_hoist(program)
    # record LAST (the rewrites' own self-checks run mid-application;
    # recording first would make them see a plan whose passes aren't
    # applied yet and V504 at the rewrite site), then self-check the
    # final composition with the plan on record — finish_pass is the
    # shared rewrite epilogue every pass uses
    from ..core.pass_framework import finish_pass
    finish_pass(program, "auto_parallel_plan", startup=startup, **meta)
    return program


# ---------------------------------------------------------------------------
# serving KV-pool sizing (planner follow-up (d))
# ---------------------------------------------------------------------------
def _model_config(model=None, config=None) -> Dict:
    """Normalize the decode model's geometry to a plain dict.  Accepts a
    ``GPTForGeneration``/``GPTModel`` (anything carrying ``.config``),
    a ``GPTConfig``-shaped object, or an already-plain dict."""
    if config is None:
        if model is None:
            raise ValueError("page_budget needs a model or a config")
        config = getattr(model, "gpt", model).config
    if isinstance(config, dict):
        src = dict(config)
    else:
        src = {k: getattr(config, k)
               for k in ("num_layers", "num_heads", "hidden_size",
                         "vocab_size", "max_position", "intermediate_size")}
    out = {k: int(src[k]) for k in ("num_layers", "num_heads",
                                    "hidden_size", "vocab_size",
                                    "max_position")}
    out["intermediate_size"] = int(
        src.get("intermediate_size") or out["hidden_size"] * 4)
    if out["hidden_size"] % out["num_heads"]:
        raise ValueError(
            f"hidden_size {out['hidden_size']} not divisible by "
            f"num_heads {out['num_heads']}")
    return out


def _decode_weight_bytes(cfg: Dict) -> int:
    """Parameter bytes of the decode model — the same shape x dtype
    persistable accounting `memory_analysis.analyze_program` charges; in
    dygraph the parameters ARE the persistables, and their shapes are
    closed forms of the config (fp32)."""
    hd, inter = cfg["hidden_size"], cfg["intermediate_size"]
    per_block = (4 * (hd * hd + hd)       # q/k/v/out projections + bias
                 + 2 * 2 * hd             # ln1/ln2 scale + shift
                 + hd * inter + inter     # fc1
                 + inter * hd + hd)       # fc2
    n = (cfg["vocab_size"] * hd           # wte (tied LM head)
         + cfg["max_position"] * hd       # wpe
         + cfg["num_layers"] * per_block
         + 2 * hd)                        # ln_f
    return n * 4


def _decode_shardable_bytes(cfg: Dict) -> int:
    """The Megatron-splittable subset of `_decode_weight_bytes`: per
    block, the q/k/v/out projection matrices (col/row split), the qkv
    biases (ride the col shard), and fc1 weight+bias / fc2 weight (col
    then row).  Embeddings, layer norms, the out-proj and fc2 biases
    (row-parallel bias applies after the allreduce) stay replicated —
    `distributed.tensor_parallel`'s exact shard set."""
    hd, inter = cfg["hidden_size"], cfg["intermediate_size"]
    per_block = (4 * hd * hd              # q/k/v/out projection matrices
                 + 3 * hd                 # q/k/v biases (col-sharded)
                 + hd * inter + inter     # fc1 weight + bias (col)
                 + inter * hd)            # fc2 weight (row)
    return cfg["num_layers"] * per_block * 4


def _decode_quantizable_counts(cfg: Dict):
    """Matrix elements and out-channels of the decode matmuls the int8
    weight stamp rewrites — q/k/v/out projections, fc1, fc2.  Biases,
    layer norms, embeddings and the tied logits matmul stay fp32.
    Out-channels split by shard class: col-parallel scales (q/k/v, fc1)
    shard with the out dim, row-parallel scales (out-proj, fc2) cover
    the full out dim on every chip."""
    hd, inter = cfg["hidden_size"], cfg["intermediate_size"]
    L = cfg["num_layers"]
    elems = L * (4 * hd * hd + 2 * hd * inter)
    col_channels = L * (3 * hd + inter)
    row_channels = L * (2 * hd)
    return elems, col_channels, row_channels


def page_budget(model=None, config=None, *, page_tokens: int = 16,
                max_context: Optional[int] = None,
                hbm_bytes: Optional[int] = None,
                weight_bytes: Optional[int] = None,
                kv_dtype: str = "float32",
                weight_dtype: str = "float32",
                max_slots_cap: Optional[int] = None,
                headroom: float = 0.08,
                draft_layers: int = 0,
                tp_degree: int = 1) -> Dict:
    """Size the serving tier's paged KV pool from the HBM walker's
    budget instead of a hand-set page count (ROADMAP planner follow-up
    (d): the same sizing authority that answers training fits/OOM).

    Accounting, per chip::

        usable    = hbm_budget_bytes() * (1 - headroom) - weight_bytes
        workspace = max_slots * (dense K+V gather view at the pow2
                    max-context bucket + a logits row)   # the decode
                    step's transient, priced because the gather-by-
                    page-table view coexists with the pool every step
        pages     = (usable - workspace) / page_bytes

    ``weight_bytes`` defaults to summing the live model's parameters —
    the identical shape x dtype persistable accounting
    ``memory_analysis.analyze_program`` performs (dygraph parameters are
    the persistables) — or the closed-form config walk when only a
    config is given.  ``hbm_bytes`` defaults to
    ``memory_analysis.hbm_budget_bytes()`` (``PADDLE_TPU_HBM_BYTES``),
    so the serving verdict and the training fits/OOM verdict share one
    budget source.

    The batch ceiling (``max_slots``) spends at most ~35% of the usable
    budget on per-step workspace — pages are the asset, the gather view
    is rent — and ``max_context`` is clamped down when the pool cannot
    hold even one worst-case sequence at the requested context.

    ``draft_layers`` charges a speculative-decoding draft model (a
    ``draft_layers``-layer sibling of the same config): its parameter
    bytes come off the usable budget and its per-slot dense KV rides
    the step workspace, so pools sized for speculative serving never
    overcommit HBM the draft needs.  The plan also carries
    ``retained_watermarks`` — the free-page low/high marks
    ``serving.RadixPrefixCache`` bounds retention with (evict LRU when
    free falls below ``low``, release down to ``high``).

    ``tp_degree`` sizes the pool for a tensor-parallel decode mesh:
    every chip holds 1/tp of the Megatron-splittable weights
    (`_decode_shardable_bytes` — attention/MLP matrices; embeddings,
    layer norms and row-parallel biases stay replicated) and 1/tp of
    every KV byte (heads shard, so each chip's page slab is
    ``[L, P, H/tp, T, Dh]``), while the logits row is replicated (the
    row-parallel head allreduces the full vocab onto every chip).  The
    HBM budget stays PER CHIP — the whole point is that a model
    infeasible at tp=1 under a pinned ``PADDLE_TPU_HBM_BYTES`` carves a
    real page pool at tp=2 because the per-chip charge shrank.  Page
    counts and contexts in the plan remain GLOBAL token geometry
    (page tables are host-side and replicated); only the byte
    accounting divides.

    ``kv_dtype="int8"`` prices pages at the int8 itemsize PLUS the
    per-(layer, page, head) fp32 scale sidecar ``PagedKVPool`` keeps
    for both K and V — that is what carves ~2× the pages at equal HBM
    (composing multiplicatively with ``tp_degree``: 2×tp× per-chip
    capacity).  The dense gather workspace stays priced at fp32: the
    pool dequantizes on read, so the decode step's transient view is
    full-precision regardless of what the pages store.  The draft's
    dense KV charge shrinks with the same itemsize (+ its scale rows).

    ``weight_dtype="int8"`` re-prices the decode weights for the
    weight-only quantization stamp: the quantizable matmul matrices
    (q/k/v/out projections, fc1, fc2) drop to 1 byte/element plus
    per-out-channel fp32 scales; biases, norms, embeddings and the
    tied logits matmul stay fp32.  Col-parallel scales shard with tp,
    row-parallel scales are replicated — the per-chip charge accounts
    for both.  The plan records the raw fp32 parameter bytes as
    ``weight_bytes_fp32`` so ``budget_drift`` can re-derive.

    Returns the plan dict ``PagedKVPool.from_plan`` consumes; every
    input is recorded in it so ``serving.kv_pool.budget_drift`` can
    re-derive the numbers and flag hand-edits, V504-style.
    """
    import numpy as np
    from .memory_analysis import hbm_budget_bytes
    cfg = _model_config(model, config)
    L, H = cfg["num_layers"], cfg["num_heads"]
    Dh = cfg["hidden_size"] // H
    T = int(page_tokens)
    if T < 1:
        raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
    tp = int(tp_degree) if tp_degree else 1
    if tp < 1:
        raise ValueError(f"tp_degree must be >= 1, got {tp_degree}")
    if H % tp:
        raise ValueError(
            f"page_budget: num_heads {H} not divisible by tp_degree "
            f"{tp} — the KV slab shards on the head dim")
    itemsize = np.dtype(kv_dtype).itemsize
    budget = int(hbm_bytes) if hbm_bytes else hbm_budget_bytes()
    if weight_bytes is None:
        if model is not None:
            weight_bytes = int(sum(
                np.asarray(p.numpy()).nbytes
                for p in getattr(model, "gpt", model).parameters()))
        else:
            weight_bytes = _decode_weight_bytes(cfg)
    weight_bytes = int(weight_bytes)
    weight_bytes_fp32 = weight_bytes
    shardable = min(weight_bytes, _decode_shardable_bytes(cfg))
    weight_dtype = str(weight_dtype)
    if weight_dtype not in ("float32", "int8"):
        raise ValueError(
            f"page_budget: weight_dtype must be float32 or int8, got "
            f"{weight_dtype!r}")
    if weight_dtype == "int8":
        elems, col_ch, row_ch = _decode_quantizable_counts(cfg)
        # matrices go 4B -> 1B; fp32 scales come back per out-channel
        weight_bytes -= elems * 3 - (col_ch + row_ch) * 4
        # the shardable set holds the matrices (now 1B) and the
        # col-parallel scales; row-parallel scales are replicated
        shardable = min(weight_bytes, shardable - elems * 3 + col_ch * 4)
    # per-chip weights: the Megatron-splittable subset divides by tp,
    # the replicated remainder (embeddings/LN/row biases) is paid whole
    weight_bytes_pc = weight_bytes - (shardable - shardable // tp)
    cap = int(max_slots_cap) if max_slots_cap else 64
    # ctx_req is the pre-clamp INPUT (recorded for budget_drift: feeding
    # the pool-clamped max_context back in would re-derive a different
    # workspace split and report drift on an untouched plan)
    ctx_req = min(int(max_context) if max_context
                  else cfg["max_position"], cfg["max_position"])
    ctx = ctx_req

    token_bytes = 2 * L * H * Dh * itemsize       # one K+V column, all layers
    page_bytes = token_bytes * T                  # global (all tp shards)
    H_loc = H // tp                               # heads resident per chip
    token_bytes_pc = 2 * L * H_loc * Dh * itemsize
    page_bytes_pc = token_bytes_pc * T
    quant_kv = np.dtype(kv_dtype) == np.int8
    if quant_kv:
        # the pool's per-(layer, page, head) fp32 scale sidecars (K and
        # V) ride every page — charged so the ~2x carve is honest
        page_bytes += 2 * L * H * 4
        page_bytes_pc += 2 * L * H_loc * 4
    # the decode step's dense gather view is DEQUANTIZED on read, so
    # the per-slot workspace stays fp32 even over int8 pages
    ws_item = 4 if quant_kv else itemsize
    ws_col_pc = 2 * L * H_loc * Dh * ws_item
    # speculative draft charge: a draft_layers-layer sibling's weights
    # are resident beside the target, and every decode slot carries a
    # dense draft KV cache at the same pow2 context bucket (both shard
    # on heads with the target, so the per-chip charge divides too)
    draft_layers = max(0, int(draft_layers))
    draft_weight_bytes = 0
    draft_weight_bytes_pc = 0
    draft_kv_slot_pc = 0
    if draft_layers:
        draft_cfg = dict(cfg)
        draft_cfg["num_layers"] = draft_layers
        draft_weight_bytes = _decode_weight_bytes(draft_cfg)
        d_shard = _decode_shardable_bytes(draft_cfg)
        draft_weight_bytes_pc = draft_weight_bytes \
            - (d_shard - d_shard // tp)
        draft_kv_slot_pc = 2 * draft_layers * H_loc * _next_pow2(ctx) \
            * Dh * itemsize
        if quant_kv:
            # the draft's dense int8 KV carries per-(layer, head)
            # fp32 scales, same sidecar layout as the pool's pages
            draft_kv_slot_pc += 2 * draft_layers * H_loc * 4
    usable = int(budget * (1.0 - float(headroom))) - weight_bytes_pc \
        - draft_weight_bytes_pc
    if usable < page_bytes_pc + ws_col_pc * _next_pow2(ctx):
        raise ValueError(
            f"page_budget: {budget} B HBM/chip leaves {usable} B after "
            f"{weight_bytes_pc} B of per-chip weights"
            + (f" + {draft_weight_bytes_pc} B of draft weights"
               if draft_layers else "") +
            f" — not enough for one decode "
            f"slot at context {ctx} at tp={tp} (raise "
            f"PADDLE_TPU_HBM_BYTES, raise tp_degree, or shrink the "
            f"model)")
    # per-slot step workspace: the dense [L, H/tp, lpad, Dh] K+V gather
    # view at the largest pow2 KV bucket, plus this row's REPLICATED
    # logits (the row-parallel head allreduces full vocab everywhere),
    # and the draft model's per-slot dense KV when speculating
    ws_slot = ws_col_pc * _next_pow2(ctx) \
        + cfg["vocab_size"] * 4 + draft_kv_slot_pc
    max_slots = max(1, min(cap, int(usable * 0.35) // ws_slot))
    pages = (usable - max_slots * ws_slot) // page_bytes_pc
    while pages < 1 and max_slots > 1:      # tiny budgets: trade slots back
        max_slots -= 1
        pages = (usable - max_slots * ws_slot) // page_bytes_pc
    if pages < 1:
        raise ValueError(
            f"page_budget: workspace for one slot leaves no room for "
            f"pages ({usable} usable, {ws_slot} per slot)")
    pages = int(pages)
    # the honest advertised max-context: ANY prompt shape within it must
    # fit its admission reservation (pages_for_request), which includes
    # the +1 COW allowance for a partial final prompt page — so the top
    # page cannot be promised (ctx = pages*T would reject in-limit
    # requests as "can never fit")
    ctx = min(ctx, max(T, (pages - 1) * T))
    max_slots = int(min(max_slots, pages))
    # retention watermarks, in FREE pages: the radix cache evicts LRU
    # leaves when free drops below `low` and releases until free climbs
    # back to `high` — retention is bounded, admission never starves
    wm_low = max(1, pages // 8)
    wm_high = max(wm_low + 1, pages // 4)
    return {
        "pages": pages,
        "page_tokens": T,
        "max_slots": max_slots,
        "max_context": int(ctx),
        "retained_watermarks": {"low": int(wm_low),
                                "high": int(min(wm_high, pages))},
        "draft_layers": draft_layers,
        "draft_weight_bytes": int(draft_weight_bytes),
        "draft_kv_bytes": int(max_slots * draft_kv_slot_pc * tp),
        "max_context_requested": int(ctx_req),
        "num_layers": L,
        "num_heads": H,
        "head_dim": Dh,
        "kv_dtype": str(kv_dtype),
        "weight_dtype": weight_dtype,
        "page_bytes": int(page_bytes),
        "kv_bytes": int(pages * page_bytes),
        "workspace_bytes": int(max_slots * ws_slot),
        "weight_bytes": weight_bytes,
        "weight_bytes_fp32": weight_bytes_fp32,
        "tp_degree": tp,
        "weight_bytes_per_chip": int(weight_bytes_pc),
        "page_bytes_per_chip": int(page_bytes_pc),
        "hbm_bytes": int(budget),
        "headroom": float(headroom),
        "max_slots_cap": cap,
        "config": cfg,
        "source": "static.page_budget (memory_analysis.hbm_budget_bytes "
                  "+ parameter persistable walk)",
    }

"""ParamAttr — per-parameter configuration (name/initializer/lr/regularizer/
trainable), analog of /root/reference/python/paddle/fluid/param_attr.py."""
from __future__ import annotations

from .initializer import Initializer, Xavier, Constant

__all__ = ["ParamAttr", "WeightNormParamAttr"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def derive(attr, suffix):
        """A NAMED ParamAttr must not be shared across differently-shaped
        weights (same-name params silently collide in the global block);
        derive a per-weight attr with `name + suffix` — the pattern
        dynamic_lstmp uses for its projection weight."""
        if isinstance(attr, ParamAttr) and attr.name:
            return ParamAttr(name=attr.name + suffix)
        return attr

    @staticmethod
    def _to_attr(arg):
        """Accept None / str (name) / Initializer / ParamAttr / False
        (fluid param_attr.py:196 _to_attr semantics; False means no param,
        used for bias_attr=False)."""
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if arg is False:
            return False
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kw):
        super().__init__(**kw)
        self.dim = dim

"""Recompute (activation checkpointing) as a backward-pass graph rewrite.

Analog of /root/reference/python/paddle/fluid/backward.py:689
`_append_backward_ops_with_checkpoints_`: forward ops are divided into
segments at user-chosen checkpoint vars; during backward, each segment's
forward ops are REPLAYED from the stored checkpoint before its grad ops run,
so only checkpoints (not every activation) stay live through the backward
sweep.

TPU-specific twist: under whole-block XLA compilation a naive replay would be
CSE'd with the original forward (XLA sees two identical pure subgraphs and
reuses the first's results — keeping the activations alive and defeating the
memory saving).  Segment inputs are therefore routed through an
`optimization_barrier` op, which XLA cannot look through; the replayed
segment is then genuinely rematerialized, matching jax.checkpoint semantics
but driven from the program IR so AMP / pipeline / fleet rewrites compose
with it the way they do in the reference.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..core.program import Block, OpDesc, OpRole, unique_name
from ..ops.registry import get_op_info

GRAD_SUFFIX = "@GRAD"


def apply_recompute(program, checkpoints=None):
    """POST-HOC activation-checkpointing rewrite of an already-minimized
    training program (forward + backward + optimizer tail in one block).

    `append_backward_with_checkpoints` below rewrites at backward-BUILD
    time, which is too early for the auto-parallel planner
    (static/planner.py): the planner receives a finished program and
    must apply every candidate knob as a rewrite on a clone.  This
    function performs the same transformation on the finished op list:

      * forward ops are segmented at `checkpoints` (default: the same
        `select_layer_checkpoints` picks FLAGS_recompute uses);
      * the first backward op that reads a non-stored activation of
        segment S triggers S's replay: an `optimization_barrier` over
        the segment's non-stored external inputs (so XLA cannot CSE the
        replay with the original forward) followed by the segment's ops
        re-emitted with ``@RC``-renamed outputs;
      * every later backward read of a segment-S activation is renamed
        to its ``@RC`` alias, so the ORIGINAL activation's live range
        ends in the forward sweep — exactly the liveness cut the memory
        walker (static/memory_analysis.py) prices.

    Replayed ops keep their original ``op_uid`` (PRNG-keyed kernels like
    dropout replay the same mask — the build-time rewrite's contract).
    Numerics are unchanged: the replay computes the same values the
    backward would have read.  Idempotent via the applied-passes
    registry: a program that already carries the "recompute" pass (from
    either rewrite path) is returned untouched.  Returns `program`.
    """
    from ..core.pass_framework import has_applied, finish_pass
    from .memory_analysis import _phase_of, select_layer_checkpoints
    if has_applied(program, "recompute"):
        return program
    if checkpoints is None:
        checkpoints = select_layer_checkpoints(program)
    ckpt_names = {c.name if hasattr(c, "name") else str(c)
                  for c in checkpoints}
    block = program.global_block()
    if not ckpt_names:
        return program

    ops = block.ops
    n_fwd = 0
    for op in ops:
        if op.type != "feed" and _phase_of(op) != "forward":
            break
        n_fwd += 1
    fwd_ops = ops[:n_fwd]
    seg_of, fresh_seg = _segment_ids(fwd_ops, ckpt_names)
    if fresh_seg == 0:
        return program  # no checkpoint var is actually produced here

    prod_seg: Dict[str, int] = {}
    for op, s in zip(fwd_ops, seg_of):
        if s == fresh_seg:
            continue
        for n in op.output_names():
            if n:
                prod_seg[n] = s

    def _stored(name: str) -> bool:
        """Safe to read in backward WITHOUT triggering a replay."""
        if name in ckpt_names:
            return True
        v = block.vars.get(name)
        return v is not None and (v.persistable or v.is_data)

    def _barrier_free(name: str) -> bool:
        """Params/data feed both passes identically; everything else a
        replay reads — INCLUDING the checkpoints — must route through
        the barrier, or XLA CSEs the replay with the original forward
        and the memory saving evaporates (build-time rewrite's
        `_is_barrier_free` contract)."""
        v = block.vars.get(name)
        return v is not None and (v.persistable or v.is_data)

    new_tail: List[OpDesc] = []
    replay_maps: Dict[int, Dict[str, str]] = {}

    def _emit_replay(seg_id: int):
        ops_in_seg = [op for op, s in zip(fwd_ops, seg_of)
                      if s == seg_id and op.type not in ("feed", "fetch")]
        produced = {n for op in ops_in_seg for n in op.output_names()}
        ext_inputs = sorted({
            n for op in ops_in_seg for n in op.input_names()
            if n and n not in produced})
        rmap: Dict[str, str] = {}

        def _alias(name: str, suffix: str) -> str:
            # replay aliases inherit the ORIGINAL var's shape/dtype (the
            # replayed op computes the same value; create_var's float32
            # default would trip the verifier's V103 on bf16/AMP casts)
            orig = block.vars.get(name)
            alias = unique_name(name + suffix)
            block.create_var(
                name=alias,
                shape=orig.shape if orig is not None else None,
                dtype=orig.dtype if orig is not None else None,
                stop_gradient=True)
            rmap[name] = alias
            return alias

        barrier_ins = [n for n in ext_inputs if not _barrier_free(n)]
        if barrier_ins:
            bar_outs = [_alias(n, "@RCB") for n in barrier_ins]
            new_tail.append(OpDesc(
                "optimization_barrier", {"X": barrier_ins},
                {"Out": bar_outs},
                {OpRole.KEY: OpRole.Backward,
                 "op_uid": program._next_uid()}))
        for op in ops_in_seg:
            new_ins = {k: [rmap.get(n, n) for n in v]
                       for k, v in op.inputs.items()}
            new_outs = {k: [_alias(n, "@RC") for n in v]
                        for k, v in op.outputs.items()}
            attrs = dict(op.attrs)  # same op_uid: replayed PRNG matches
            attrs[OpRole.KEY] = OpRole.Backward
            new_tail.append(OpDesc(op.type, new_ins, new_outs, attrs))
        replay_maps[seg_id] = rmap

    for op in ops[n_fwd:]:
        if _phase_of(op) == "backward":
            needed = sorted({
                prod_seg[n] for n in op.input_names()
                if n and n in prod_seg and not _stored(n)})
            for s in needed:
                if s not in replay_maps:
                    _emit_replay(s)
            if needed:
                for k, v in op.inputs.items():
                    op.inputs[k] = [
                        replay_maps.get(prod_seg.get(n, -1), {}).get(n, n)
                        for n in v]
        new_tail.append(op)
    block.ops = fwd_ops + new_tail
    program._fingerprint_cache = None
    finish_pass(program, "recompute", checkpoints=len(ckpt_names),
                post_hoc=True)
    return program


def _segment_ids(fwd_ops: List[OpDesc], checkpoints: Set[str]):
    """Assign each forward op a segment id; segment boundary AFTER an op that
    produces a checkpoint var.  Ops after the last checkpoint form the final
    'fresh' segment which is never replayed (its activations are still hot
    when backward starts)."""
    seg = []
    cur = 0
    for op in fwd_ops:
        seg.append(cur)
        if any(n in checkpoints for n in op.output_names()):
            cur += 1
    return seg, cur  # cur == id of the fresh (non-replayed) segment


def append_backward_with_checkpoints(block: Block, loss, parameter_list,
                                     no_grad: Set[str], checkpoints):
    from .backward import _find_loss_op_idx, _requires_grad_vars, \
        grad_var_name
    program = block.program
    ckpt_names = {c.name if hasattr(c, "name") else str(c)
                  for c in checkpoints}
    loss_idx = _find_loss_op_idx(block, loss.name)
    fwd_ops = block.ops[: loss_idx + 1]
    seg_of, fresh_seg = _segment_ids(fwd_ops, ckpt_names)
    req = _requires_grad_vars(block, fwd_ops) - set(no_grad)

    # names safe to read without replay: checkpoints, persistables (params),
    # data inputs — everything else produced inside a replayed segment gets
    # a per-segment @RC alias
    def _stored(name: str) -> bool:
        if name in ckpt_names:
            return True
        try:
            v = block.var(name)
        except KeyError:
            return False
        return v.persistable or v.is_data

    with program._op_role_guard(OpRole.Backward):
        g_loss = block.create_var(
            name=grad_var_name(loss.name), shape=loss.shape,
            dtype=loss.dtype, stop_gradient=True)
        block.append_op(
            "fill_constant", outputs={"Out": g_loss},
            attrs={"shape": (list(loss.shape) if loss.shape is not None
                             else [1]),
                   "dtype": loss.dtype, "value": 1.0})

        pending: Dict[str, List[str]] = {loss.name: [g_loss.name]}
        grad_map: Dict[str, str] = {}

        def _settle(name):
            pieces = pending.get(name)
            if not pieces:
                return None
            if len(pieces) == 1:
                grad_map[name] = pieces[0]
                return pieces[0]
            out = unique_name(grad_var_name(name) + "@SUM")
            block.create_var(name=out, stop_gradient=True)
            block.append_op("sum", inputs={"X": list(pieces)},
                            outputs={"Out": out})
            pending[name] = [out]
            grad_map[name] = out
            return out

        # replay maps: segment id -> {orig name -> replayed name}
        replay_maps: Dict[int, Dict[str, str]] = {}

        def _emit_replay(seg_id: int):
            """Re-emit segment seg_id's forward ops with @RC-renamed outputs,
            inputs routed through an optimization_barrier."""
            if seg_id in replay_maps or seg_id == fresh_seg:
                return
            ops_in_seg = [op for op, s in zip(fwd_ops, seg_of) if s == seg_id]
            produced = {n for op in ops_in_seg for n in op.output_names()}
            ext_inputs = sorted({
                n for op in ops_in_seg for n in op.input_names()
                if n not in produced})
            rmap: Dict[str, str] = {}
            barrier_ins = [n for n in ext_inputs if not _is_barrier_free(n)]
            if barrier_ins:
                bar_outs = []
                for n in barrier_ins:
                    alias = unique_name(n + "@RCB")
                    block.create_var(name=alias, stop_gradient=True)
                    rmap[n] = alias
                    bar_outs.append(alias)
                block.append_op("optimization_barrier",
                                inputs={"X": barrier_ins},
                                outputs={"Out": bar_outs})
            for op in ops_in_seg:
                new_ins = {k: [rmap.get(n, n) for n in v]
                           for k, v in op.inputs.items()}
                new_outs = {}
                for k, v in op.outputs.items():
                    outs = []
                    for n in v:
                        alias = unique_name(n + "@RC")
                        block.create_var(name=alias, stop_gradient=True)
                        rmap[n] = alias
                        outs.append(alias)
                    new_outs[k] = outs
                rop = block.append_op(op.type, new_ins, new_outs,
                                      attrs=dict(op.attrs))
                rop.attrs["op_uid"] = op.attrs.get("op_uid", 0)  # same RNG
            replay_maps[seg_id] = rmap

        def _is_barrier_free(name: str) -> bool:
            # params/data feed both passes identically; barrier only needed
            # on vars whose live range we want to cut (checkpoints and any
            # stored intermediate)
            try:
                v = block.var(name)
            except KeyError:
                return False
            return v.persistable or v.is_data

        for i in range(len(fwd_ops) - 1, -1, -1):
            op = fwd_ops[i]
            info = get_op_info(op.type)
            if info is None or not info.has_grad:
                continue
            out_has_grad = any(n in pending for n in op.output_names())
            in_requires = any(
                n in req
                for slot in info.inputs if not slot.no_grad
                for n in op.inputs.get(slot.name, []))
            if not (out_has_grad and in_requires):
                continue

            seg_id = seg_of[i]
            _emit_replay(seg_id)
            rmap = replay_maps.get(seg_id, {})

            g_inputs: Dict[str, List[str]] = {}
            for slot in info.inputs:
                names = op.inputs.get(slot.name, [])
                if names:
                    g_inputs[slot.name] = [rmap.get(n, n) for n in names]
            for slot in info.outputs:
                names = op.outputs.get(slot.name, [])
                if names:
                    g_inputs[slot.name] = [rmap.get(n, n) for n in names]
                    gnames = []
                    for n in names:
                        g = _settle(n)
                        gnames.append(g if g is not None else "")
                    if any(gnames):
                        g_inputs[slot.name + GRAD_SUFFIX] = gnames

            g_outputs: Dict[str, List[str]] = {}
            for slot in info.inputs:
                if slot.no_grad:
                    continue
                names = op.inputs.get(slot.name, [])
                outs = []
                for n in names:
                    if n not in req or n in no_grad:
                        outs.append("")
                        continue
                    piece = unique_name(grad_var_name(n))
                    block.create_var(name=piece, stop_gradient=True)
                    pending.setdefault(n, []).append(piece)
                    outs.append(piece)
                if any(outs):
                    g_outputs[slot.name + GRAD_SUFFIX] = outs
            if not g_outputs:
                continue
            gop = block.append_op(info.grad_op_type(), g_inputs, g_outputs,
                                  attrs=dict(op.attrs))
            gop.attrs[OpRole.KEY] = OpRole.Backward
            gop.attrs["fwd_uid"] = op.attrs.get("op_uid", 0)

        for name in list(pending):
            _settle(name)

    program._grad_map.update(grad_map)

    from ..core.program import VarDesc
    if parameter_list is not None:
        params = [p if isinstance(p, VarDesc) else
                  program.global_block().var(p) for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    result = []
    for p in params:
        g = grad_map.get(p.name)
        if g is None:
            continue
        gv = block.var(g)
        gv.shape = p.shape
        gv.dtype = gv.dtype or p.dtype
        result.append((p, gv))
    from ..core.pass_framework import finish_pass
    finish_pass(program, "recompute", checkpoints=len(ckpt_names))
    return result

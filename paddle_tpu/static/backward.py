"""append_backward: graph-level reverse-mode autodiff by op rewriting.

Analog of /root/reference/python/paddle/fluid/backward.py:1275 append_backward
(and _append_backward_ops_ :922, _append_backward_vars_ :1103).  Walks the
block's ops in reverse, appending each op's grad op (slot convention from
paddle_tpu.ops.registry._register_grad), accumulating duplicate gradients with
sum ops (the reference's @RENAME@ mechanism).

Kept as a *program rewrite* rather than jax.grad so that AMP / recompute /
gradient-merge / pipeline meta-optimizers can rewrite the backward graph the
same way the reference does (SURVEY.md §7 stage 5).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core.program import Program, Block, OpDesc, VarDesc, OpRole, unique_name
from ..ops.registry import get_op_info

__all__ = ["append_backward", "grad_var_name", "gradients",
           "_find_loss_op_idx"]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def _requires_grad_vars(block: Block, ops: List[OpDesc]) -> Set[str]:
    """Forward sweep: vars that (transitively) depend on a trainable param or
    a non-stop-gradient var."""
    req: Set[str] = set()
    for v in block.program.global_block().vars.values():
        if v.is_parameter and v.trainable:
            req.add(v.name)
        elif v.is_data and not v.stop_gradient:
            # data vars default stop_gradient=True (fluid semantics); an
            # explicitly unfrozen input is a grad leaf (fluid.gradients)
            req.add(v.name)
    for v in block.vars.values():
        if v.is_data and not v.stop_gradient:
            req.add(v.name)
    for op in ops:
        info = get_op_info(op.type)
        if info is None or not info.has_grad:
            continue
        needs = False
        for slot in info.inputs:
            if slot.no_grad:
                continue
            for n in op.inputs.get(slot.name, []):
                if n in req:
                    needs = True
        if needs:
            for n in op.output_names():
                try:
                    if not block.var(n).stop_gradient:
                        req.add(n)
                except KeyError:
                    req.add(n)
    return req


def _find_loss_op_idx(block: Block, loss_name: str) -> int:
    for i in range(len(block.ops) - 1, -1, -1):
        if loss_name in block.ops[i].output_names():
            return i
    raise ValueError(f"loss var {loss_name!r} is not produced in this block")


# reentrancy guard: the auto-remat estimate builds a plain backward on a
# CLONE of the program; that nested append_backward must not re-enter
# the auto hook
_in_auto_remat_estimate = False


def _auto_remat_checkpoints(loss, block: Block, no_grad: Set[str]):
    """FLAGS_recompute-driven checkpoint selection (None = plain
    backward).  ``always``: checkpoint every transformer-layer boundary.
    ``auto``: additionally build the UNREWRITTEN backward on a clone,
    walk its liveness (memory_analysis), and rewrite only when the
    predicted peak exceeds the HBM budget — so remat's extra FLOPs are
    paid exactly when the memory is actually needed."""
    global _in_auto_remat_estimate
    if _in_auto_remat_estimate:
        return None
    from ..core.flags import flag
    mode = str(flag("recompute", "") or "").strip().lower()
    if mode in ("", "0", "off", "false", "none"):
        return None
    from .memory_analysis import select_layer_checkpoints, analyze_program
    program = block.program
    ckpts = select_layer_checkpoints(program)
    if not ckpts:
        return None
    if mode == "auto":
        clone = program.clone()
        try:
            clone_loss = clone.global_block().var(loss.name)
        except KeyError:
            return None
        _in_auto_remat_estimate = True
        try:
            append_backward(clone_loss, None, set(no_grad), checkpoints=())
        finally:
            _in_auto_remat_estimate = False
        report = analyze_program(clone)
        # The decision runs BEFORE minimize() appends optimizer ops, so
        # the clone walk is missing the optimizer's persistable slots.
        # Reserve 2x trainable-param bytes for them (Adam/Lamb moments,
        # the common case) so this verdict matches the post-minimize
        # walk bench.py reports — without the reserve a config could be
        # declared fitting here and over-budget in the same JSON record.
        import numpy as _np
        from ..core.dtype import np_dtype as _np_dtype
        reserve = 0
        for p in program.all_parameters():
            if p.trainable and p.shape is not None and p.dtype is not None:
                n = 1
                for d in p.shape:
                    n *= 1 if d in (-1, None) else int(d)
                reserve += n * _np.dtype(_np_dtype(p.dtype)).itemsize
        # world-size-aware slot accounting: under ZeRO-1 sharding
        # (FLAGS_hbm_dp_shard, distributed/sharding.py) the moments this
        # reserve models are split 1/N per chip — the verdict must match
        # the sharded post-minimize walk, not the replicated one
        ds = int(flag("hbm_dp_shard", 0)) or 1
        if report["peak_bytes"] + 2 * reserve // ds \
                <= report["fits_budget_bytes"]:
            return None
    return ckpts


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append grad ops for `loss` to its program; returns
    [(param VarDesc, grad VarDesc)] like the reference (backward.py:1275).

    checkpoints: list of var (names) to use for recompute segmentation
    (reference backward.py:689) — routed through
    static/recompute_rewrite.py.  With ``checkpoints=None``,
    ``FLAGS_recompute`` engages auto-remat: ``always`` rewrites at
    transformer-layer boundaries unconditionally, ``auto`` only when the
    HBM estimator (static/memory_analysis.py) predicts the
    ``PADDLE_TPU_HBM_BYTES`` budget is exceeded.  Pass ``checkpoints=[]``
    to force the plain backward regardless of the flag.
    """
    block = loss.block if loss.block is not None else None
    if block is None:
        from ..core.program import default_main_program
        block = default_main_program().global_block()
    program: Program = block.program
    loss_name = loss.name
    no_grad = set(no_grad_set or ())

    if checkpoints is None:
        checkpoints = _auto_remat_checkpoints(loss, block, no_grad)
    if checkpoints:
        from .recompute_rewrite import append_backward_with_checkpoints
        return append_backward_with_checkpoints(
            block, loss, parameter_list, no_grad, checkpoints)

    loss_idx = _find_loss_op_idx(block, loss_name)
    fwd_ops = block.ops[: loss_idx + 1]
    req = _requires_grad_vars(block, fwd_ops)
    req -= no_grad

    # mark the loss op for pipeline/AMP passes (reference uses op_role Loss)
    block.ops[loss_idx].attrs[OpRole.KEY] = int(OpRole.Forward | OpRole.Loss)

    with program._op_role_guard(OpRole.Backward):
        # seed: d loss / d loss = 1
        g_loss = block.create_var(
            name=grad_var_name(loss_name), shape=loss.shape,
            dtype=loss.dtype, stop_gradient=True)
        static_shape = (loss.shape is not None
                        and all(d is not None and d >= 0
                                for d in loss.shape))
        if static_shape:
            block.append_op(
                "fill_constant", outputs={"Out": g_loss},
                attrs={"shape": list(loss.shape), "dtype": loss.dtype,
                       "value": 1.0, OpRole.KEY: OpRole.Backward})
        else:
            # non-scalar target with a symbolic batch dim (gradients() on
            # an intermediate grad var): seed ones at the runtime shape
            block.append_op(
                "fill_any_like", inputs={"X": [loss_name]},
                outputs={"Out": g_loss},
                attrs={"value": 1.0, OpRole.KEY: OpRole.Backward})

        # pending grad pieces per var: var -> [grad piece names]
        pending: Dict[str, List[str]] = {loss_name: [g_loss.name]}
        grad_map: Dict[str, str] = {}

        def _settle(name: str) -> Optional[str]:
            """Collapse accumulated grad pieces of `name` into one var."""
            pieces = pending.get(name)
            if not pieces:
                return None
            if len(pieces) == 1:
                grad_map[name] = pieces[0]
                return pieces[0]
            out = grad_var_name(name)
            if out in pieces or block.has_var(out):
                # already taken by a piece or by a previous append_backward
                # (double grad): never clobber an existing grad var
                out = unique_name(grad_var_name(name) + "@SUM")
            # stop_gradient=False: grad vars stay differentiable so a second
            # append_backward (double grad via <op>_grad_grad) can flow
            # through them
            v = block.create_var(name=out, stop_gradient=False)
            block.append_op("sum", inputs={"X": list(pieces)},
                            outputs={"Out": out})
            pending[name] = [out]
            grad_map[name] = out
            return out

        for op in reversed(fwd_ops):
            info = get_op_info(op.type)
            if info is None or not info.has_grad:
                continue
            out_has_grad = any(n in pending for n in op.output_names())
            in_requires = any(
                n in req
                for slot in info.inputs if not slot.no_grad
                for n in op.inputs.get(slot.name, []))
            if not (out_has_grad and in_requires):
                continue

            g_inputs: Dict[str, List[str]] = {}
            for slot in info.inputs:
                names = op.inputs.get(slot.name, [])
                if names:
                    g_inputs[slot.name] = list(names)
            for slot in info.outputs:
                names = op.outputs.get(slot.name, [])
                if names:
                    g_inputs[slot.name] = list(names)
                    gnames = []
                    for n in names:
                        g = _settle(n)
                        gnames.append(g if g is not None else "")
                    if any(gnames):
                        g_inputs[slot.name + GRAD_SUFFIX] = gnames

            g_outputs: Dict[str, List[str]] = {}
            for slot in info.inputs:
                if slot.no_grad:
                    continue
                names = op.inputs.get(slot.name, [])
                outs = []
                for n in names:
                    if n not in req or n in no_grad:
                        outs.append("")
                        continue
                    piece = unique_name(grad_var_name(n))
                    block.create_var(name=piece, stop_gradient=False)
                    pending.setdefault(n, []).append(piece)
                    outs.append(piece)
                if any(outs):
                    g_outputs[slot.name + GRAD_SUFFIX] = outs

            if not g_outputs:
                continue
            gop = block.append_op(info.grad_op_type(), g_inputs, g_outputs,
                                  attrs=dict(op.attrs))
            gop.attrs[OpRole.KEY] = OpRole.Backward
            gop.attrs["fwd_uid"] = op.attrs.get("op_uid", 0)

        # settle every remaining pending var (params & inputs)
        for name in list(pending):
            _settle(name)

    program._grad_map.update(grad_map)

    if parameter_list is not None:
        params = [p if isinstance(p, VarDesc) else
                  program.global_block().var(p) for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    result = []
    for p in params:
        g = grad_map.get(p.name)
        if g is None:
            continue
        gv = block.var(g)
        gv.shape = p.shape
        gv.dtype = gv.dtype or p.dtype
        result.append((p, gv))
        # record for op_role_var (used by DGC/AMP passes in the reference)
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients — grads of targets w.r.t. arbitrary inputs
    (reference backward.py:1823)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "gradients(): single target supported"
    pairs = append_backward(targets[0], parameter_list=None,
                            no_grad_set=no_grad_set)
    block = targets[0].block
    program = block.program
    outs = []
    for x in inputs:
        g = program._grad_map.get(x.name)
        outs.append(block.var(g) if g else None)
    return outs

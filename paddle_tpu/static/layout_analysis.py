"""Sharding-propagation analyzer: static SPMD layout inference over a
2-D mesh, with reshard detection and wire pricing.

GSPMD's core move — whole-graph sharding propagation from a handful of
annotations — applied to this framework's op IR: `propagate_shardings`
assigns every var in a Program a PartitionSpec-shaped layout over named
mesh axes (``dp`` for the data-parallel world, ``mp`` for the
tensor/model-parallel ring — the runtime "tp" mesh axis under its
canonical analysis name, ``sp`` for the sequence ring), starting from

  * ``dist_attr`` parameter annotations (`tensor_parallel.shard_param`),
  * ``dp_shard`` ZeRO bucket stamps (`distributed/sharding.py`),
  * caller partition rules matched through
    `distributed.partition_spec.match_partition_rules` (the tp row/col
    vocabulary lives there: ``MP_COL``/``MP_ROW``/
    ``tensor_parallel_rules``),

and running per-op propagation rules to a forward/backward fixed point:
matmul contraction/batch dims (a row-parallel contraction mints a
PARTIAL sum pending its reduction), elementwise broadcast joins,
reshape/transpose dim tracking (attention head splits ride the split
heads dim), and collectives as explicit layout converters
(``c_identity`` the Megatron f, ``mp_allreduce_sum`` the g clearing the
partial, ``c_concat``/``c_split`` gather/scatter of the feature dim).

On top of the inferred layouts the analyzer reports the V6xx diagnostic
family (stable codes, `static.check_program(level="layout")` — see
docs/static_analysis.md):

  V601  layout conflict — an op consumes operands whose inferred specs
        are incompatible with its kernel contract (the row-parallel fc
        fed a replicated input it would double-count).
  V602  missing reduction — a partial-sum output is read as if complete
        (the dropped-``mp_allreduce_sum``-after-row-parallel bug).
  V603  redundant reshard — a gather/reduction the program pays wire
        for that propagation proves unnecessary.
  V604  mesh-axis disagreement — a collective stamped/rung for one mesh
        axis whose operand is sharded or partial over another.
  V605  tp-degree ∤ dim — a sharded dim's declared size does not divide
        the mesh degree of its axis.

It also emits the **reshard table**: one row per layout-converting
collective (var, from-spec, to-spec, axis, bytes), priced through
`verifier.entry_wire_bytes` with each ring's OWN degree — the per-axis
wire substrate the auto-parallel planner needs before it can search
``dp × tp`` plans, and the correctness gate every 2-D candidate runs
through.

Diagnostics are conservative by construction: they concern the MODEL
axes only (``mp``/``sp``) — ``dp`` batch semantics are the V2xx
collective checker's jurisdiction — so a program with no
tensor-parallel structure can never produce a V6xx finding, and an op
the analyzer cannot model taints its outputs instead of guessing
(tainted vars are exempt from the redundant-reshard check).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core.program import Block, OpDesc, OpRole, Program
from .verifier import (Diagnostic, ERROR, _dtype_bytes, _numel,
                       entry_wire_bytes, ring_axis)

__all__ = ["LayoutSpec", "ShardingLayout", "propagate_shardings",
           "MODEL_AXES"]

# axes whose layouts this analyzer adjudicates; "dp" is tracked (ZeRO
# bucket shards, reshard-table rows) but never generates V6xx findings
MODEL_AXES = frozenset(("mp", "sp"))

# the runtime mesh spells the model axis "tp" (CompiledProgram); the
# analyzer canonicalizes to "mp" (the ROADMAP's dp × mp vocabulary) —
# both via the ONE shared table in core/mesh_axes.py
from ..core.mesh_axes import canonical_axis as _canon


class LayoutSpec:
    """One var's inferred layout: a PartitionSpec-shaped tuple (axis
    name per dim, None = replicated dim, trailing Nones trimmed) plus
    the set of axes the value is a PARTIAL sum over (a pending
    reduction: reading it as complete is the V602 bug)."""

    __slots__ = ("spec", "partial")

    def __init__(self, spec: Sequence = (), partial=()):
        spec = tuple(spec)
        while spec and spec[-1] is None:
            spec = spec[:-1]
        self.spec = spec
        self.partial = frozenset(partial)

    def axis_at(self, dim: int) -> Optional[str]:
        return self.spec[dim] if 0 <= dim < len(self.spec) else None

    def dim_of(self, axis: str) -> Optional[int]:
        for i, a in enumerate(self.spec):
            if a == axis:
                return i
        return None

    def axes(self) -> Set[str]:
        return {a for a in self.spec if a}

    def model_axes(self) -> Set[str]:
        return self.axes() & MODEL_AXES

    def model_partial(self) -> Set[str]:
        return set(self.partial) & MODEL_AXES

    @property
    def replicated(self) -> bool:
        return not self.spec and not self.partial

    def with_axis(self, dim: int, axis: Optional[str]) -> "LayoutSpec":
        spec = list(self.spec) + [None] * max(0, dim + 1 - len(self.spec))
        spec[dim] = axis
        return LayoutSpec(spec, self.partial)

    def without_axis(self, axis: str) -> "LayoutSpec":
        return LayoutSpec([None if a == axis else a for a in self.spec],
                          self.partial - {axis})

    def with_partial(self, *axes) -> "LayoutSpec":
        return LayoutSpec(self.spec, self.partial | set(axes))

    def cleared(self, axis: str) -> "LayoutSpec":
        return LayoutSpec(self.spec, self.partial - {axis})

    def __eq__(self, other):
        return (isinstance(other, LayoutSpec) and self.spec == other.spec
                and self.partial == other.partial)

    def __hash__(self):
        return hash((self.spec, self.partial))

    def render(self) -> str:
        body = ", ".join("None" if a is None else repr(a)
                         for a in self.spec)
        s = f"P({body})"
        if self.partial:
            s += "+partial(" + ",".join(sorted(self.partial)) + ")"
        return s

    def __repr__(self):
        return f"LayoutSpec({self.render()})"


_REPL = LayoutSpec()


# ---------------------------------------------------------------------------
# op classification
# ---------------------------------------------------------------------------
# layout-preserving ops: output layout == input layout, forward AND
# backward (the fill-in direction of the fixed point)
_COPY_OPS = frozenset((
    "relu", "gelu", "sigmoid", "tanh", "scale", "cast", "assign",
    "dropout", "exp", "log", "sqrt", "square", "abs", "clip", "elu",
    "leaky_relu", "relu6", "softplus", "softsign", "swish",
    "hard_sigmoid", "hard_swish", "sin", "cos", "rsqrt", "floor",
    "ceil", "round", "logical_not", "increment", "c_identity",
    "scale_by_world_size", "share_data", "print",
))

_EW_BINARY = frozenset((
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_pow", "elementwise_min",
    "elementwise_max", "less_than", "less_equal", "greater_than",
    "greater_equal", "equal", "not_equal", "logical_and", "logical_or",
))

_REDUCTION_COLLECTIVES = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "mp_allreduce_sum", "c_reducescatter",
    "c_elastic_fold",
))

_GATHER_COLLECTIVES = frozenset((
    "c_concat", "c_allgather", "partial_allgather",
))

# ops that reduce over explicit dims (attrs decide which)
_REDUCE_OPS = frozenset((
    "mean", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod",
))


def _role(op: OpDesc) -> int:
    return int(op.attrs.get(OpRole.KEY, OpRole.Forward))


def _is_optimize(op: OpDesc) -> bool:
    return bool(_role(op) & OpRole.Optimize)


def _shape_of(block: Block, name: Optional[str]):
    if not name:
        return None
    try:
        v = block.var(name)
    except KeyError:
        return None
    return tuple(v.shape) if v.shape is not None else None


def _first(names) -> Optional[str]:
    return names[0] if names else None


# ---------------------------------------------------------------------------
# result object
# ---------------------------------------------------------------------------
class ShardingLayout:
    """`propagate_shardings`' verdict: per-var layouts, V6xx
    diagnostics, and the priced reshard table."""

    def __init__(self, specs: Dict[str, LayoutSpec],
                 diagnostics: List[Diagnostic],
                 reshard_table: List[dict],
                 mesh_shape: Dict[str, int], iterations: int):
        self.specs = dict(specs)
        self.diagnostics = list(diagnostics)
        self.reshard_table = list(reshard_table)
        self.mesh_shape = dict(mesh_shape)
        self.iterations = int(iterations)

    def spec(self, name: str) -> LayoutSpec:
        return self.specs.get(name, _REPL)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def wire_bytes_per_axis(self) -> Dict[str, int]:
        """Per-mesh-axis ICI bytes one rank moves per step across the
        reshard table (ring-algorithm accounting via
        `verifier.entry_wire_bytes`, each ring priced at its own
        degree)."""
        out: Dict[str, float] = {}
        for row in self.reshard_table:
            out[row["axis"]] = out.get(row["axis"], 0.0) + row["bytes"]
        return {a: int(b) for a, b in out.items()}

    def wire_bytes(self, axis: Optional[str] = None) -> int:
        per = self.wire_bytes_per_axis()
        if axis is not None:
            return per.get(_canon(axis), 0)
        return int(sum(per.values()))

    def render_reshard_table(self) -> str:
        head = "| var | op | axis | from | to | bytes |"
        rows = [head, "|---|---|---|---|---|---|"]
        for r in self.reshard_table:
            rows.append(f"| {r['var']} | {r['op_type']} | {r['axis']} | "
                        f"{r['from']} | {r['to']} | {r['bytes']} |")
        return "\n".join(rows)

    def __repr__(self):
        n_model = sum(1 for s in self.specs.values() if s.model_axes()
                      or s.model_partial())
        return (f"ShardingLayout({len(self.specs)} vars, {n_model} "
                f"model-sharded, {len(self.errors)} errors, "
                f"{len(self.reshard_table)} reshards)")


# ---------------------------------------------------------------------------
# the propagation engine
# ---------------------------------------------------------------------------
class _Engine:
    def __init__(self, program: Program, mesh_shape: Dict[str, int],
                 batch: Optional[int]):
        self.program = program
        self.block = program.global_block()
        self.mesh = mesh_shape
        self.batch = batch
        self.specs: Dict[str, LayoutSpec] = {}
        self.pinned: Set[str] = set()
        self.tainted: Set[str] = set()
        self.diags: List[Diagnostic] = []
        self.reshard: List[dict] = []
        self.collect = False
        # cascade control: a partial/conflicted var is reported once
        self._reported: Set[Tuple[str, str]] = set()
        self._changed = False

    # -- state ---------------------------------------------------------------
    def get(self, name: Optional[str]) -> LayoutSpec:
        if not name:
            return _REPL
        return self.specs.get(name, _REPL)

    def set(self, name: Optional[str], spec: LayoutSpec):
        if not name or name in self.pinned:
            return
        if self.specs.get(name, _REPL) != spec:
            self.specs[name] = spec
            self._changed = True

    def taint(self, *names):
        for n in names:
            if n and n not in self.tainted:
                self.tainted.add(n)
                self._changed = True

    def pin(self, name: str, spec: LayoutSpec):
        self.specs[name] = spec
        self.pinned.add(name)

    # -- diagnostics ---------------------------------------------------------
    def diag(self, code: str, msg: str, op: Optional[OpDesc] = None,
             op_idx: Optional[int] = None, var: Optional[str] = None,
             severity: str = ERROR):
        if not self.collect:
            return
        key = (code, var or (f"op{op_idx}" if op_idx is not None else msg))
        if key in self._reported:
            return
        self._reported.add(key)
        self.diags.append(Diagnostic(
            code, severity, msg, block_idx=0, op_idx=op_idx,
            op_type=op.type if op is not None else None,
            op_uid=op.attrs.get("op_uid") if op is not None else None,
            var=var))

    # -- wire pricing --------------------------------------------------------
    def _nbytes(self, name: Optional[str]) -> Optional[int]:
        shape = _shape_of(self.block, name)
        if shape is None:
            return None
        if self.batch and shape and int(shape[0]) < 0:
            shape = (int(self.batch),) + tuple(shape[1:])
        n = _numel(shape)
        if n is None:
            return None
        try:
            dt = self.block.var(name).dtype
        except KeyError:
            dt = None
        return n * _dtype_bytes(dt)

    def _reshard_row(self, op: OpDesc, op_idx: int, axis: Optional[str],
                     in_name: Optional[str], from_spec: LayoutSpec,
                     to_spec: LayoutSpec):
        if not self.collect or axis is None:
            return
        degree = int(self.mesh.get(axis) or 0)
        nbytes = self._nbytes(in_name)
        try:
            x_dp_shard = int(self.block.var(in_name).attrs.get("dp_shard")
                             or 0) if in_name else 0
        except KeyError:
            x_dp_shard = 0
        entry = {
            "type": op.type, "ring_id": int(op.attrs.get("ring_id", 0)),
            "nbytes": nbytes, "dp_degree": degree if axis == "dp" else None,
            "tp_degree": degree if axis != "dp" else None,
            "mp_axis": axis if axis in MODEL_AXES else None,
            "x_dp_shard": x_dp_shard,
        }
        priced = entry_wire_bytes(entry, degree or 1) if degree else 0.0
        self.reshard.append({
            "var": in_name, "op_type": op.type,
            "op_uid": op.attrs.get("op_uid"), "block": 0, "index": op_idx,
            "axis": axis, "ring_id": entry["ring_id"],
            "degree": degree or None,
            "from": from_spec.render(), "to": to_spec.render(),
            "bytes": int(priced),
        })

    # -- axis resolution -----------------------------------------------------
    def _op_axis(self, op: OpDesc) -> Optional[str]:
        """The mesh axis a collective's RING binds to.  Deliberately
        ignores the ``mp_axis`` stamp: the ring is what the program
        actually executes, the stamp is the builder's declared intent —
        V604 is their disagreement (`_stamped_axis` vs this)."""
        return _canon(ring_axis(int(op.attrs.get("ring_id", 0))))

    def _stamped_axis(self, op: OpDesc) -> Optional[str]:
        return _canon(op.attrs.get("mp_axis"))

    # -- the partial gate ----------------------------------------------------
    def _consume(self, op: OpDesc, op_idx: int,
                 name: Optional[str]) -> LayoutSpec:
        """Read `name` for a non-reduction consumption: a model-axis
        partial sum read here is the missing-reduction bug (V602).
        Returns the spec with reported partials cleared so one dropped
        reduction reports once, not at every downstream op."""
        spec = self.get(name)
        pend = spec.model_partial()
        if pend and name:
            self.diag(
                "V602",
                f"op reads {name!r}, a PARTIAL sum over mesh axis(es) "
                f"{sorted(pend)} that no reduction collective has "
                f"completed — the value is 1/degree of the true result "
                f"on every rank (a row-parallel allreduce was dropped "
                f"or mis-placed)", op=op, op_idx=op_idx, var=name)
            for a in pend:
                spec = spec.cleared(a)
            if not self.collect:
                return spec
            # persist the clearing so downstream ops don't cascade
            if name not in self.pinned:
                self.specs[name] = spec
        return spec

    # -- transfer functions --------------------------------------------------
    def transfer(self, op: OpDesc, op_idx: int):
        t = op.type
        if t in ("feed", "fetch"):
            return
        if t.endswith("_grad") or _is_optimize(op):
            # backward/optimizer tails: cotangent slot conventions and
            # in-place sharded updates are out of scope here (V2xx/V3xx
            # own them) — outputs default replicated, no diagnostics
            for n in op.output_names():
                self.set(n, _REPL)
            return

        if t in _COPY_OPS:
            return self._copy(op, op_idx)
        if t in _EW_BINARY or t == "where":
            return self._elementwise(op, op_idx)
        if t == "sum":
            return self._ew_join(op, op_idx, op.inputs.get("X", []))
        if t == "mul":
            return self._mul(op, op_idx)
        if t == "int8_matmul":
            return self._int8_matmul(op, op_idx)
        if t == "matmul":
            return self._matmul(op, op_idx)
        if t in ("reshape", "reshape2"):
            return self._reshape(op, op_idx)
        if t in ("transpose", "transpose2"):
            return self._transpose(op, op_idx)
        if t in ("softmax", "log_softmax"):
            return self._softmax(op, op_idx)
        if t == "softmax_with_cross_entropy":
            return self._softmax_xent(op, op_idx)
        if t == "layer_norm":
            return self._layer_norm(op, op_idx)
        if t in _REDUCE_OPS:
            return self._reduce(op, op_idx)
        if t in _REDUCTION_COLLECTIVES:
            return self._reduction_collective(op, op_idx)
        if t in _GATHER_COLLECTIVES:
            return self._gather(op, op_idx)
        if t == "c_split":
            return self._split_collective(op, op_idx)
        if t in ("c_broadcast", "broadcast"):
            x = _first(op.inputs.get("X", []))
            self._consume(op, op_idx, x)
            self.set(_first(op.outputs.get("Out", [])), _REPL)
            return
        if t == "flash_attention":
            q = _first(op.inputs.get("Q", []))
            spec = self._consume(op, op_idx, q)
            self.set(_first(op.outputs.get("Out", [])), spec)
            return
        if t == "concat":
            return self._concat(op, op_idx)
        # unknown op: partial reads still gate; model-sharded inputs
        # taint the outputs rather than guessing a layout
        model_in = False
        for n in op.input_names():
            spec = self._consume(op, op_idx, n)
            if spec.model_axes() or n in self.tainted:
                model_in = True
        for n in op.output_names():
            self.set(n, _REPL)
            if model_in:
                self.taint(n)

    # -- per-family rules ----------------------------------------------------
    def _copy(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        spec = self._consume(op, op_idx, x)
        out = _first(op.output_names())
        self.set(out, spec)
        if x in self.tainted:
            self.taint(out)

    def _align(self, out_rank: int, in_rank: int, axis_attr: int) -> int:
        """Fluid elementwise broadcast: Y dim j aligns to X dim
        offset+j, offset = axis attr (or trailing alignment).  The axis
        attr only positions the lower-rank (broadcast) operand — a
        full-rank operand always aligns at 0, so a bias add with
        axis=rank-1 must not shift the activation's own dims."""
        if out_rank is not None and in_rank >= out_rank:
            return 0
        if axis_attr is not None and axis_attr >= 0:
            return int(axis_attr)
        return max(0, out_rank - in_rank)

    def _ew_join(self, op: OpDesc, op_idx: int, names):
        out = _first(op.output_names())
        out_shape = _shape_of(self.block, out)
        out_rank = len(out_shape) if out_shape is not None else None
        joined: Dict[int, str] = {}
        conflict = None
        tainted = False
        for n in names:
            spec = self._consume(op, op_idx, n)
            tainted |= n in self.tainted
            in_shape = _shape_of(self.block, n)
            in_rank = len(in_shape) if in_shape is not None else \
                len(spec.spec)
            off = self._align(out_rank if out_rank is not None else in_rank,
                              in_rank, op.attrs.get("axis", -1)
                              if op.type in _EW_BINARY else -1)
            for j in range(len(spec.spec)):
                a = spec.spec[j]
                if not a:
                    continue
                d = off + j
                prev = joined.get(d)
                if prev is not None and prev != a and \
                        a in MODEL_AXES and prev in MODEL_AXES:
                    conflict = (d, prev, a, n)
                joined[d] = a
        # one operand sharded on a model axis where another operand
        # carries a real (>1) extent replicated: the kernel would add a
        # local shard to a full tensor — a layout conflict
        for n in names:
            spec = self.get(n)
            in_shape = _shape_of(self.block, n)
            if in_shape is None:
                continue
            in_rank = len(in_shape)
            off = self._align(out_rank if out_rank is not None else in_rank,
                              in_rank, op.attrs.get("axis", -1)
                              if op.type in _EW_BINARY else -1)
            for d, a in joined.items():
                if a not in MODEL_AXES:
                    continue
                j = d - off
                if 0 <= j < in_rank and spec.axis_at(j) != a and \
                        int(in_shape[j]) not in (1,) and \
                        int(in_shape[j]) >= 0 and n not in self.tainted:
                    # a -1 (batch) dim can't be a feature shard target;
                    # skip unknown extents to stay conservative
                    self.diag(
                        "V601",
                        f"elementwise {op.type!r} mixes a {a!r}-sharded "
                        f"operand with {n!r}, replicated over the same "
                        f"dim (extent {in_shape[j]}): each rank would "
                        f"combine a local shard with a full tensor",
                        op=op, op_idx=op_idx, var=n)
        if conflict is not None:
            d, a1, a2, n = conflict
            self.diag(
                "V601",
                f"elementwise {op.type!r} operands disagree on dim {d} "
                f"layout ({a1!r} vs {a2!r})", op=op, op_idx=op_idx, var=n)
        if out_rank is None and joined:
            out_rank = max(joined) + 1
        spec_list = [None] * (out_rank or 0)
        for d, a in joined.items():
            if d < len(spec_list):
                spec_list[d] = a
        self.set(out, LayoutSpec(spec_list))
        if tainted:
            self.taint(out)

    def _elementwise(self, op: OpDesc, op_idx: int):
        names = [n for slot in ("Condition", "X", "Y")
                 for n in op.inputs.get(slot, [])]
        if not names:
            names = op.input_names()
        self._ew_join(op, op_idx, names)

    def _mul(self, op: OpDesc, op_idx: int):
        """fluid `mul`: X flattened at x_num_col_dims (m), Y at
        y_num_col_dims (k).  Out = X[:m] ⊗ Y[k:]; contraction = X[m:]
        against Y[:k].  The Megatron contracts live here: a
        column-parallel weight (Y out-dim sharded) shards the output
        features; a row-parallel weight (Y in-dim sharded) demands a
        matching feature-sharded X and mints a PARTIAL output."""
        x = _first(op.inputs.get("X", []))
        y = _first(op.inputs.get("Y", []))
        out = _first(op.outputs.get("Out", []))
        m = int(op.attrs.get("x_num_col_dims", 1))
        k = int(op.attrs.get("y_num_col_dims", 1))
        self._mul_like(op, op_idx, x, y, out, m, k)

    def _int8_matmul(self, op: OpDesc, op_idx: int):
        """Weight-only int8 matmul (the serving decode stamp): X
        contracts its LAST dim against W [K, N] — `mul` semantics with
        m = rank(X) - 1, k = 1, so the Megatron col/row contracts carry
        over unchanged.  WScale is per-out-channel: it must shard with
        W's out dim (column-parallel) or stay replicated
        (row-parallel); anything else rescales one chip's channels
        with another's scales."""
        x = _first(op.inputs.get("X", []))
        w = _first(op.inputs.get("W", []))
        out = _first(op.outputs.get("Out", []))
        s = _first(op.inputs.get("WScale", []))
        ws = self.get(w)
        a_col = next((a for j, a in enumerate(ws.spec)
                      if a in MODEL_AXES and j >= 1), None)
        if s is not None:
            ss = self._consume(op, op_idx, s)
            if ss.axis_at(0) != a_col and s not in self.tainted \
                    and w not in self.tainted:
                self.diag(
                    "V601",
                    f"int8_matmul scale {s!r} is laid out "
                    f"{ss.render()} but weight {w!r}'s out-channels "
                    f"are {'sharded over ' + repr(a_col) if a_col else 'replicated'}"
                    f" — per-channel dequant would apply the wrong "
                    f"chip's scales", op=op, op_idx=op_idx, var=s)
        for n in op.inputs.get("Bias", []):
            self._consume(op, op_idx, n)
        x_shape = _shape_of(self.block, x)
        xs = self.get(x)
        rank = len(x_shape) if x_shape is not None \
            else max(len(xs.spec), 2)
        self._mul_like(op, op_idx, x, w, out, rank - 1, 1)

    def _mul_like(self, op: OpDesc, op_idx: int, x, y, out,
                  m: int, k: int):
        xs = self._consume(op, op_idx, x)
        ys = self._consume(op, op_idx, y)

        a_x = next((a for j, a in enumerate(xs.spec)
                    if a in MODEL_AXES and j >= m), None)
        a_row = next((a for j, a in enumerate(ys.spec)
                      if a in MODEL_AXES and j < k), None)
        a_col = next((a for j, a in enumerate(ys.spec)
                      if a in MODEL_AXES and j >= k), None)

        partial: Set[str] = set()
        if a_row and a_x == a_row:
            partial.add(a_row)       # proper row-parallel contraction
        elif a_row and not (x in self.tainted):
            self.diag(
                "V601",
                f"row-parallel weight {y!r} (in-features sharded over "
                f"{a_row!r}) consumes {x!r} whose contraction dims are "
                f"{'sharded over ' + repr(a_x) if a_x else 'replicated'}"
                f" — each rank would contract the FULL input against "
                f"its weight shard and the reduced sum double-counts "
                f"(feed it a column-parallel output)",
                op=op, op_idx=op_idx, var=x)
            partial.add(a_row)
        elif a_x and not a_row and y is not None and \
                x not in self.tainted:
            self.diag(
                f"V601",
                f"op contracts {x!r}, feature-sharded over {a_x!r}, "
                f"against replicated weight {y!r}: each rank sees only "
                f"1/degree of the features (missing gather, or the "
                f"weight lost its row-parallel annotation)",
                op=op, op_idx=op_idx, var=x)

        out_spec = list(xs.spec[:m]) + [None]
        # Y's out dims land at out dim m.. ; y dims k.. map in order
        y_shape = _shape_of(self.block, y)
        y_rank = len(y_shape) if y_shape is not None else len(ys.spec)
        for j in range(k, max(y_rank, len(ys.spec))):
            a = ys.axis_at(j)
            d = m + (j - k)
            while len(out_spec) <= d:
                out_spec.append(None)
            out_spec[d] = a
        self.set(out, LayoutSpec(out_spec, partial))
        if x in self.tainted or y in self.tainted:
            self.taint(out)

    def _matmul(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        y = _first(op.inputs.get("Y", []))
        out = _first(op.outputs.get("Out", []))
        tx = bool(op.attrs.get("transpose_X"))
        ty = bool(op.attrs.get("transpose_Y"))
        xs = self._consume(op, op_idx, x)
        ys = self._consume(op, op_idx, y)
        x_shape = _shape_of(self.block, x)
        y_shape = _shape_of(self.block, y)
        if x_shape is None or y_shape is None or len(x_shape) < 2 or \
                len(y_shape) < 2:
            self.set(out, _REPL)
            if xs.model_axes() or ys.model_axes():
                self.taint(out)
            return
        rx, ry = len(x_shape), len(y_shape)
        out_rank = max(rx, ry)
        # batch dims broadcast-align from the TRAILING side (out dim i
        # ↔ x dim i-(out_rank-rx) ↔ y dim i-(out_rank-ry)); a
        # rank-mismatched operand simply has no counterpart for the
        # leading out dims
        out_spec: List[Optional[str]] = [None] * out_rank
        conflict_var = None
        for i in range(out_rank - 2):
            ix, iy = i - (out_rank - rx), i - (out_rank - ry)
            xa = xs.axis_at(ix) if ix >= 0 else None
            ya = ys.axis_at(iy) if iy >= 0 else None
            if xa and ya and xa != ya and xa in MODEL_AXES and \
                    ya in MODEL_AXES:
                conflict_var = x
            out_spec[i] = xa or ya
        if conflict_var:
            self.diag(
                "V601",
                f"matmul batch dims of {x!r} and {y!r} are sharded over "
                f"different mesh axes", op=op, op_idx=op_idx,
                var=conflict_var)
        xc = rx - 2 if tx else rx - 1            # x contraction dim
        yc = ry - 1 if ty else ry - 2            # y contraction dim
        xo = rx - 1 if tx else rx - 2            # x out (row) dim
        yo = ry - 2 if ty else ry - 1            # y out (col) dim
        partial: Set[str] = set()
        ca, cb = xs.axis_at(xc), ys.axis_at(yc)
        if ca and ca in MODEL_AXES and ca == cb:
            partial.add(ca)
        elif (ca in MODEL_AXES or cb in MODEL_AXES) and ca != cb and \
                x not in self.tainted and y not in self.tainted:
            one = ca if ca in MODEL_AXES else cb
            self.diag(
                "V601",
                f"matmul contraction dim sharded over {one!r} on one "
                f"operand only ({x!r} vs {y!r}): the local products "
                f"contract mismatched slices", op=op, op_idx=op_idx,
                var=x if ca else y)
        out_spec[out_rank - 2] = xs.axis_at(xo)
        out_spec[out_rank - 1] = ys.axis_at(yo)
        self.set(out, LayoutSpec(out_spec, partial))
        if x in self.tainted or y in self.tainted:
            self.taint(out)

    def _reshape(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        spec = self._consume(op, op_idx, x)
        in_shape = _shape_of(self.block, x)
        out_shape = _shape_of(self.block, out) or \
            tuple(op.attrs.get("shape", ()))
        if not spec.axes():
            self.set(out, LayoutSpec((), spec.partial))
            if x in self.tainted:
                self.taint(out)
            return
        if in_shape is None or not out_shape:
            self.set(out, LayoutSpec((), spec.partial))
            self.taint(out)
            return
        # dim tracking: equal-size leading dims map identity; the FIRST
        # dim past that prefix absorbs the split/merge (the attention
        # head split [b,t,H]→[b,t,h,d] and its inverse merge keep the
        # shard on the heads dim).  A shard deeper than that is beyond
        # this tracker — taint instead of guessing.
        p = 0
        while p < min(len(in_shape), len(out_shape)) and \
                (int(in_shape[p]) == int(out_shape[p]) or
                 int(in_shape[p]) < 0 or int(out_shape[p]) < 0):
            # a -1 dim is the symbolic batch — it matches any extent,
            # so a concrete-batch producer feeding a -1-declared
            # reshape still maps the prefix identity
            p += 1
        out_spec: List[Optional[str]] = [None] * len(out_shape)
        lost = False
        for i, a in enumerate(spec.spec):
            if not a:
                continue
            if i < p and i < len(out_spec):
                out_spec[i] = a
            elif i == p and p < len(out_spec):
                out_spec[p] = a
            else:
                lost = a in MODEL_AXES
        self.set(out, LayoutSpec(out_spec, spec.partial))
        if lost or x in self.tainted:
            self.taint(out)
        xshape = _first(op.outputs.get("XShape", []))
        if xshape:
            self.set(xshape, _REPL)

    def _transpose(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        spec = self._consume(op, op_idx, x)
        perm = [int(a) for a in (op.attrs.get("axis") or ())]
        if not perm:
            self.set(out, spec)
            return
        out_spec = [spec.axis_at(perm[j]) for j in range(len(perm))]
        self.set(out, LayoutSpec(out_spec, spec.partial))
        if x in self.tainted:
            self.taint(out)
        xshape = _first(op.outputs.get("XShape", []))
        if xshape:
            self.set(xshape, _REPL)

    def _softmax(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        spec = self._consume(op, op_idx, x)
        shape = _shape_of(self.block, x)
        ax = int(op.attrs.get("axis", -1))
        if shape is not None and ax < 0:
            ax += len(shape)
        a = spec.axis_at(ax) if ax >= 0 else None
        if a in MODEL_AXES and x not in self.tainted:
            self.diag(
                "V601",
                f"{op.type} normalizes over dim {ax} of {x!r}, which is "
                f"sharded over {a!r}: each rank normalizes its local "
                f"slice only (gather first, or shard a different dim)",
                op=op, op_idx=op_idx, var=x)
        self.set(_first(op.outputs.get("Out", [])), spec)

    def _softmax_xent(self, op: OpDesc, op_idx: int):
        logits = _first(op.inputs.get("Logits", []))
        spec = self._consume(op, op_idx, logits)
        shape = _shape_of(self.block, logits)
        last = len(shape) - 1 if shape is not None else None
        if last is not None and spec.axis_at(last) in MODEL_AXES and \
                logits not in self.tainted:
            self.diag(
                "V601",
                f"softmax_with_cross_entropy over {logits!r} whose class "
                f"dim is sharded over {spec.axis_at(last)!r}: the local "
                f"softmax normalizes 1/degree of the vocabulary",
                op=op, op_idx=op_idx, var=logits)
        for slot in ("Softmax", "Loss"):
            self.set(_first(op.outputs.get(slot, [])), _REPL)

    def _layer_norm(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        spec = self._consume(op, op_idx, x)
        shape = _shape_of(self.block, x)
        bna = int(op.attrs.get("begin_norm_axis", 1))
        if shape is not None and x not in self.tainted:
            for d in range(bna, len(shape)):
                if spec.axis_at(d) in MODEL_AXES:
                    self.diag(
                        "V601",
                        f"layer_norm normalizes dims {bna}.. of {x!r} "
                        f"but dim {d} is sharded over "
                        f"{spec.axis_at(d)!r}: per-rank statistics "
                        f"diverge from the full-row norm",
                        op=op, op_idx=op_idx, var=x)
                    break
        self.set(_first(op.outputs.get("Y", [])), spec)
        for slot in ("Mean", "Variance"):
            self.set(_first(op.outputs.get(slot, [])), _REPL)

    def _reduce(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        spec = self._consume(op, op_idx, x)
        shape = _shape_of(self.block, x)
        rank = len(shape) if shape is not None else len(spec.spec)
        if op.type == "mean" or op.attrs.get("reduce_all"):
            dims = list(range(rank))
        else:
            dims = [int(d) % rank if rank else int(d)
                    for d in (op.attrs.get("dim") or [0])]
        partial = set(spec.partial)
        for d in dims:
            a = spec.axis_at(d)
            if a in MODEL_AXES:
                # summing/averaging a locally-sharded dim yields a
                # partial result pending a cross-rank reduction
                partial.add(a)
        keep = op.attrs.get("keep_dim") or op.attrs.get("keepdim")
        out_spec = [a if (i not in dims) else None
                    for i, a in enumerate(spec.spec)]
        if not keep:
            out_spec = [a for i, a in enumerate(out_spec) if i not in dims]
        self.set(out, LayoutSpec(out_spec, partial))
        if x in self.tainted:
            self.taint(out)

    # -- collectives as layout converters ------------------------------------
    def _reduction_collective(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        ring_ax = self._op_axis(op)
        stamp_ax = self._stamped_axis(op)
        spec = self.get(x)
        pend = spec.model_partial()
        if stamp_ax and ring_ax != stamp_ax:
            self.diag(
                "V604",
                f"collective {op.type!r} is stamped for mesh axis "
                f"{stamp_ax!r} but rides ring "
                f"{int(op.attrs.get('ring_id', 0))} "
                f"({ring_ax!r}): the reduction completes over the wrong "
                f"device group", op=op, op_idx=op_idx, var=x)
        if pend and ring_ax not in pend:
            self.diag(
                "V604",
                f"{op.type!r} reduces over {ring_ax!r} but its operand "
                f"{x!r} is partial over {sorted(pend)}: the pending "
                f"sum is never completed on the right axis",
                op=op, op_idx=op_idx, var=x)
            # clear anyway so the miss reports here, not at every
            # downstream read
            new = spec
            for a in pend:
                new = new.cleared(a)
            self.set(out, new)
            return
        if ring_ax in MODEL_AXES:
            if ring_ax in spec.axes():
                self.diag(
                    "V604",
                    f"{op.type!r} reduces over {ring_ax!r} but {x!r} is "
                    f"SHARDED over that axis: ranks would sum disjoint "
                    f"slices elementwise", op=op, op_idx=op_idx, var=x)
            elif not pend and x not in self.tainted:
                self.diag(
                    "V603",
                    f"{op.type!r} on the {ring_ax!r} ring reduces "
                    f"{x!r}, which propagation proves complete (not a "
                    f"partial sum): the program pays "
                    f"2(g-1)/g wire for a no-op (or scales the value "
                    f"by the ring degree)", op=op, op_idx=op_idx, var=x)
        new = spec.cleared(ring_ax) if ring_ax else spec
        self.set(out, new)
        if x in self.tainted:
            self.taint(out)
        self._reshard_row(op, op_idx, ring_ax, x, spec, new)

    def _gather(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        ring_ax = self._op_axis(op)
        stamp_ax = self._stamped_axis(op)
        spec = self._consume(op, op_idx, x)
        if stamp_ax and ring_ax != stamp_ax:
            self.diag(
                "V604",
                f"gather {op.type!r} is stamped for mesh axis "
                f"{stamp_ax!r} but rides ring "
                f"{int(op.attrs.get('ring_id', 0))} ({ring_ax!r})",
                op=op, op_idx=op_idx, var=x)
        if ring_ax in MODEL_AXES:
            if ring_ax in spec.axes():
                new = spec.without_axis(ring_ax)
            else:
                if x not in self.tainted:
                    self.diag(
                        "V603",
                        f"{op.type!r} gathers {x!r} over {ring_ax!r}, "
                        f"but propagation proves it already replicated "
                        f"on that axis: the program pays (g-1)× wire "
                        f"for an implicit reshard it does not need",
                        op=op, op_idx=op_idx, var=x)
                new = spec
        else:
            # dp-ring gathers (ZeRO publishes/JIT gathers) re-replicate
            new = spec.without_axis("dp") if ring_ax == "dp" else spec
        self.set(out, new)
        if x in self.tainted:
            self.taint(out)
        self._reshard_row(op, op_idx, ring_ax, x, spec, new)

    def _split_collective(self, op: OpDesc, op_idx: int):
        x = _first(op.inputs.get("X", []))
        out = _first(op.outputs.get("Out", []))
        ring_ax = self._op_axis(op)
        spec = self._consume(op, op_idx, x)
        new = spec
        if ring_ax in MODEL_AXES:
            shape = _shape_of(self.block, out) or \
                _shape_of(self.block, x)
            last = (len(shape) - 1) if shape else 0
            new = spec.with_axis(last, ring_ax)
        self.set(out, new)
        self._reshard_row(op, op_idx, ring_ax, x, spec, new)

    def _concat(self, op: OpDesc, op_idx: int):
        names = op.inputs.get("X", [])
        specs = [self._consume(op, op_idx, n) for n in names]
        out = _first(op.outputs.get("Out", []))
        ax = int(op.attrs.get("axis", 0))
        if specs and all(s == specs[0] for s in specs) and \
                specs[0].axis_at(ax) is None:
            self.set(out, specs[0])
        else:
            self.set(out, _REPL)
            if any(s.model_axes() for s in specs):
                self.taint(out)

    # -- backward (fill-in) sweep --------------------------------------------
    def backward_fill(self, op: OpDesc):
        """The backward leg of the fixed point: layout-preserving and
        dim-permuting ops pull a consumer-side spec back onto inputs no
        forward rule assigned (rule-seeded intermediates, vars whose
        producer the tracker had to taint)."""
        t = op.type
        if t in _COPY_OPS:
            x = _first(op.inputs.get("X", []))
            out = _first(op.output_names())
            if x and x not in self.specs and out in self.specs:
                spec = self.specs[out]
                if spec.axes():
                    self.set(x, LayoutSpec(spec.spec))
        elif t in ("transpose", "transpose2"):
            x = _first(op.inputs.get("X", []))
            out = _first(op.outputs.get("Out", []))
            perm = [int(a) for a in (op.attrs.get("axis") or ())]
            if x and perm and x not in self.specs and out in self.specs:
                spec = self.specs[out]
                if spec.axes():
                    inv: List[Optional[str]] = [None] * len(perm)
                    for j, p in enumerate(perm):
                        if p < len(inv):
                            inv[p] = spec.axis_at(j)
                    self.set(x, LayoutSpec(inv))

    # -- driver --------------------------------------------------------------
    def run(self) -> int:
        iters = 0
        while iters < 16:
            iters += 1
            self._changed = False
            for i, op in enumerate(self.block.ops):
                self.transfer(op, i)
            for op in reversed(self.block.ops):
                self.backward_fill(op)
            if not self._changed:
                break
        self.collect = True
        for i, op in enumerate(self.block.ops):
            self.transfer(op, i)
        self._check_divisibility()
        return iters

    def _local_shape_region(self) -> Set[str]:
        """Vars whose DECLARED shapes are build-time LOCAL shards: the
        downstream closure of every head-split reshape whose known-dim
        numel drops by exactly the degree of a model axis THE OUTPUT IS
        SHARDED OVER (parallel_attention reshapes [b, t, H] globals
        into [b, t, H/tp/d, d] locals — the division is baked into the
        target shape).  V605 must not judge these extents against the
        mesh degree: they are already divided.  The closure ends where
        the local representation does — at the reduction/gather
        collectives that return values to the global representation
        (the row-parallel g, tensor-ring gathers), so vars after the
        block boundary are judged normally again."""
        local: Set[str] = set()
        for op in self.block.ops:
            if op.type in _REDUCTION_COLLECTIVES or \
                    op.type in _GATHER_COLLECTIVES:
                continue  # outputs are global-representation again
            seeded = False
            if op.type in ("reshape", "reshape2"):
                x = _first(op.inputs.get("X", []))
                out = _first(op.outputs.get("Out", []))
                in_shape = _shape_of(self.block, x)
                out_shape = _shape_of(self.block, out)
                out_axes = self.get(out).model_axes() if out else set()
                if in_shape is not None and out_shape is not None and \
                        out_axes:
                    pin = pout = 1
                    for v in in_shape:
                        if int(v) > 0:
                            pin *= int(v)
                    for v in out_shape:
                        if int(v) > 0:
                            pout *= int(v)
                    for a in out_axes:
                        g = int(self.mesh.get(a) or 0)
                        if g > 1 and pout > 0 and pin == pout * g:
                            seeded = True
            if seeded or any(n in local for n in op.input_names()):
                local.update(n for n in op.output_names() if n)
        return local

    def _check_divisibility(self):
        """V605: a model-axis shard whose declared dim does not divide
        the mesh degree of its axis.  Vars in the build-time-local
        region (see `_local_shape_region`) are exempt — their extents
        already encode the division."""
        producers: Dict[str, Tuple[int, OpDesc]] = {}
        for i, op in enumerate(self.block.ops):
            for n in op.output_names():
                if n and n not in producers:
                    producers[n] = (i, op)
        local = self._local_shape_region()
        for name, spec in sorted(self.specs.items()):
            if name in local:
                continue
            for d, a in enumerate(spec.spec):
                if a not in MODEL_AXES:
                    continue
                g = int(self.mesh.get(a) or 0)
                if g <= 1:
                    continue
                shape = _shape_of(self.block, name)
                if shape is None or d >= len(shape):
                    continue
                s = int(shape[d])
                if s > 0 and s % g != 0:
                    i, op = producers.get(name, (None, None))
                    self.diag(
                        "V605",
                        f"var {name!r} dim {d} (extent {s}) is sharded "
                        f"over {a!r} but does not divide the mesh "
                        f"degree {g}: the shard split is ill-formed",
                        op=op, op_idx=i, var=name)


# ---------------------------------------------------------------------------
# seeding + entry point
# ---------------------------------------------------------------------------
def _infer_mesh_shape(program: Program) -> Dict[str, int]:
    """Best-effort mesh degrees when the caller passes none: the mp
    degree from the builders' ``tp_degree`` stamps / registry entries,
    the dp degree from the recorded ZeRO plan or collective stamps."""
    mesh: Dict[str, int] = {}
    from ..core.pass_framework import applied_passes
    for e in applied_passes(program):
        if e.get("pass") == "tensor_parallel" and e.get("tp_degree"):
            mesh["mp"] = max(mesh.get("mp", 0), int(e["tp_degree"]))
    for b in program.blocks:
        for op in b.ops:
            if op.attrs.get("tp_degree"):
                mesh["mp"] = max(mesh.get("mp", 0),
                                 int(op.attrs["tp_degree"]))
            if op.attrs.get("dp_degree"):
                mesh["dp"] = max(mesh.get("dp", 0),
                                 int(op.attrs["dp_degree"]))
    plan = getattr(program, "_zero_shard_plan", None)
    if plan is not None and getattr(plan, "buckets", None):
        mesh["dp"] = int(plan.dp_degree)
    return mesh


def _seed(engine: _Engine, rules) -> None:
    program = engine.program
    # 1. builder annotations: dist_attr = [axis, dim]
    for b in program.blocks:
        for v in b.vars.values():
            da = v.attrs.get("dist_attr")
            if da:
                axis, dim = _canon(da[0]), int(da[1])
                engine.pin(v.name, LayoutSpec(
                    [None] * dim + [axis]))
            elif v.attrs.get("dp_shard"):
                engine.pin(v.name, LayoutSpec(("dp",)))
    # 2. caller partition rules over qualified names (param:/var:),
    #    first match wins; rule specs use the partition_spec spelling
    if rules:
        from ..distributed.partition_spec import match_partition_rules
        names, backing = [], {}
        for b in program.blocks:
            for v in b.vars.values():
                q = (f"param:{v.name}" if v.is_parameter
                     else f"var:{v.name}")
                names.append(q)
                backing[q] = v.name
        assignment = match_partition_rules(rules, names)
        for q, spec in assignment.specs.items():
            if assignment.rule_of.get(q) is None:
                continue  # fallback no-match: leave to propagation
            name = backing[q]
            if name in engine.pinned:
                continue  # builder annotations outrank name rules
            engine.pin(name, LayoutSpec([_canon(a) for a in spec]))


def propagate_shardings(program: Program,
                        mesh_shape: Optional[Dict[str, int]] = None,
                        rules=None,
                        batch: Optional[int] = None) -> ShardingLayout:
    """Infer a full SPMD layout for `program` over a named 2-D mesh and
    report V6xx layout diagnostics plus the priced reshard table.

    * ``mesh_shape`` — axis degrees, e.g. ``{"dp": 4, "mp": 2}`` (the
      runtime spelling ``{"dp": 4, "tp": 2}`` is accepted).  Omitted
      axes default to the degrees stamped on the program (builder
      ``tp_degree`` stamps, ZeRO ``dp_degree``); degrees the analyzer
      cannot learn disable the divisibility check (V605) and zero the
      wire pricing for that axis.
    * ``rules`` — ordered partition rules (`distributed.partition_spec`
      spelling) matched against ``param:<name>`` / ``var:<name>``
      qualified names as extra layout seeds; builder ``dist_attr``
      annotations always win.
    * ``batch`` — bind the leading -1 feed dim for wire pricing
      (activations' reshard bytes are batch-proportional; unbound they
      price 0 and the table row records the shapes anyway).

    Returns a `ShardingLayout`: ``specs`` (var → `LayoutSpec`),
    ``diagnostics`` (V601-V605 with op provenance), ``reshard_table``
    (one row per layout-converting collective: var, from-spec, to-spec,
    axis, ring-accounted bytes via `verifier.entry_wire_bytes` at the
    ring's own degree), ``wire_bytes_per_axis()``.

    Wired as level 5 (``"layout"``) of `static.check_program`; the
    auto-parallel planner consumes ``wire_bytes_per_axis`` as the
    mp-ring wire substrate for 2-D plan search.
    """
    inferred = _infer_mesh_shape(program)
    mesh: Dict[str, int] = dict(inferred)
    for k, v in (mesh_shape or {}).items():
        mesh[_canon(k)] = int(v)
    engine = _Engine(program, mesh, batch)
    _seed(engine, rules)
    iters = engine.run()
    return ShardingLayout(engine.specs, engine.diags, engine.reshard,
                          mesh, iters)

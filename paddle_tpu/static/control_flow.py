"""Control flow: While / cond / case / switch_case / Switch / StaticRNN.

Reference: /root/reference/python/paddle/fluid/layers/control_flow.py
(`While` :1020, `cond` :1976, `case` :2753, `switch_case` :3331, `Switch`
:1461, `StaticRNN` :411) and the C++ ops
/root/reference/paddle/fluid/operators/controlflow/while_op.cc:1,
conditional_block_op.cc:1, operators/recurrent_op.cc.

TPU-native redesign (NOT a translation of the reference's scope-pushing
executors):

  * builders create real sub-Blocks in the Program (multi-block IR, same as
    the reference), recording the sub-block's free variables and
    parent-variable writes at build time;
  * the kernels (ops/kernels/control.py) recursively trace the sub-block
    with BlockTracer and lower to XLA-native control flow:
        while             -> jax.lax.while_loop   (not differentiable)
        cond              -> jax.lax.cond         (differentiable)
        static_rnn        -> jax.lax.scan         (differentiable, the
                             TPU-idiomatic recurrent lowering: compiled
                             loop, O(1) graph size, remat-friendly)
        conditional_block -> masked merge: both sides compute,
                             where(cond, new, old) selects (the XLA
                             `select` trade — see
                             distributed/fleet/meta_optimizers/
                             rewrite_utils.py for the doctrine)
  * everything stays inside the ONE whole-block jit of the executor — no
    host round trips between iterations.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.program import (Block, OpDesc, Program, VarDesc,
                            default_main_program, unique_name)
from .layer_helper import LayerHelper

__all__ = ["While", "while_loop", "cond", "case", "switch_case", "Switch",
           "StaticRNN", "DynamicRNN",
           "increment", "less_than", "array_write", "array_read",
           "array_length", "create_array",
           "lod_rank_table", "max_sequence_len", "lod_tensor_to_array",
           "array_to_lod_tensor", "reorder_lod_tensor_by_rank",
           "shrink_memory", "split_lod_tensor", "merge_lod_tensor"]


# re-exported conveniences (reference keeps these in control_flow.py)
def increment(x, value=1.0, in_place=True):
    from . import layers
    return layers.increment(x, value=value, in_place=in_place)


def less_than(x, y, force_cpu=None, cond=None):
    from . import layers
    return layers.less_than(x, y, cond=cond)


# ---------------------------------------------------------------------------
# sub-block analysis
# ---------------------------------------------------------------------------
def _analyze_block(sub: Block) -> Tuple[List[str], List[str]]:
    """Return (free_vars, written_parent_vars) of a sub-block, in first-use
    order.

    free: names read before any op in the block writes them — their values
    must be supplied by the enclosing scope.
    written_parent: names written by the block that resolve to a variable of
    an ANCESTOR block (loop-carried / branch-assigned state) — everything
    else the block writes is a local temporary.
    """
    defined: set = set()
    free: List[str] = []
    written: List[str] = []
    for op in sub.ops:
        for n in op.input_names():
            if n and n not in defined and n not in free:
                free.append(n)
        for n in op.output_names():
            if n:
                defined.add(n)
                if n not in written:
                    written.append(n)

    def _in_ancestor(name: str) -> bool:
        b = (sub.program.blocks[sub.parent_idx]
             if sub.parent_idx >= 0 else None)
        while b is not None:
            if name in b.vars:
                return True
            b = (sub.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        return False

    written_parent = [n for n in written
                      if n not in sub.vars and _in_ancestor(n)]
    return free, written_parent


@contextlib.contextmanager
def _sub_block(program: Program):
    sub = program.create_block()
    try:
        yield sub
    finally:
        program.rollback()


def append_while_op(parent: Block, sub: Block, cond_name: str,
                    is_test: bool = False, max_iters: int = 0,
                    strict_truncation: bool = False):
    """Analyze a closed while sub-block and append the `while` op to the
    parent (single producer of the op schema — While.block(), while_loop
    and the dy2static loop recorder all route here).  max_iters > 0 makes
    the loop reverse-differentiable (masked lax.scan lowering).  Returns
    (free, written)."""
    free, written = _analyze_block(sub)
    if cond_name not in written:
        raise ValueError(
            "While body never updates the loop condition "
            f"{cond_name!r}; the loop would not terminate")
    snap_of: Dict[str, str] = {}
    if max_iters:
        # The while op overwrites its carried vars IN PLACE (fluid
        # semantics), so by backward time their pre-loop values are gone
        # and the grad op's forward replay would start from the FINAL
        # state (condition already false → zero iterations → zero grads).
        # Snapshot each carried input through a differentiable assign;
        # the while reads its carry inits from the snapshots, and
        # assign_grad routes the init cotangent back to the real
        # producer.  (The reference preserves per-iteration scopes
        # instead — while_op.cc:167 WhileGradOp — a host-side tape with
        # no XLA equivalent.)  Unused snapshots are DCE'd by XLA.
        for c in written:
            try:
                v = parent.var(c)
            except KeyError:
                continue
            snap = unique_name(c + "@PRELOOP")
            parent.create_var(name=snap, shape=v.shape, dtype=v.dtype,
                              stop_gradient=v.stop_gradient)
            parent.append_op("assign", inputs={"X": [c]},
                             outputs={"Out": [snap]}, attrs={})
            snap_of[c] = snap
    x_names = list(dict.fromkeys(
        [snap_of.get(n, n) for n in free if n != cond_name]
        + [snap_of.get(n, n) for n in written]))
    carry_srcs = [snap_of.get(n, n) for n in written]
    parent.append_op(
        "while",
        inputs={"Condition": [snap_of.get(cond_name, cond_name)],
                "X": x_names},
        outputs={"Out": list(written)},
        attrs={"sub_block": sub.idx, "x_names": x_names,
               "carry_names": list(written), "carry_srcs": carry_srcs,
               "cond_name": cond_name,
               "is_test": is_test, "max_iters": int(max_iters or 0),
               "strict_truncation": bool(strict_truncation)})
    if max_iters and not is_test:
        # differentiable (bounded) loop: loop vars are usually created by
        # fill_constant, whose output carries stop_gradient=True — but the
        # while WRITES them with values that depend on its inputs, so the
        # float carried state must become gradient-bearing whenever any
        # input requires grad, or append_backward's requires-grad sweep
        # (backward.py _requires_grad_vars) never reaches past the loop.
        # Only vars produced by constant INITIALIZER ops are flipped — a
        # carried var the user computed and explicitly froze keeps its
        # stop_gradient=True.
        _init_ops = {"fill_constant", "fill_constant_batch_size_like",
                     "fill_zeros_like", "fill_any_like", "assign_value",
                     "zeros_like", "ones_like"}
        init_produced = {n for op in parent.ops if op.type in _init_ops
                         for n in op.output_names()}

        def _requires(name):
            try:
                v = parent.var(name)
            except KeyError:
                return False
            return (v.is_parameter and v.trainable) or not v.stop_gradient
        if any(_requires(n) for n in x_names):
            for n in written:
                if n not in init_produced:
                    continue
                try:
                    v = parent.var(n)
                except KeyError:
                    continue
                if v.dtype in ("float32", "float64", "float16", "bfloat16"):
                    v.stop_gradient = False
    return free, written


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------
class While:
    """while-loop over a sub-block (control_flow.py:1020 `While`).

        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...body ops, must update `cond` (e.g. via layers.less_than
            with output into cond) or the loop never ends...

    Loop-carried variables are discovered automatically: every parent
    variable the body writes is carried (it must hold a value before the
    loop).  Lowered to jax.lax.while_loop — NOT differentiable; train
    recurrences with StaticRNN (lax.scan) instead.
    """

    def __init__(self, cond: VarDesc, is_test: bool = False, name=None,
                 max_iters: int = 0, strict_truncation: bool = False):
        if cond.dtype not in ("bool",):
            raise TypeError("While condition must be a bool variable, got "
                            f"{cond.dtype}")
        if cond.shape is not None and tuple(cond.shape) not in ((), (1,)):
            raise TypeError("While condition must be a scalar (shape [1]), "
                            f"got {cond.shape}")
        self.cond_var = cond
        self.program = (cond.block.program if cond.block is not None
                        else default_main_program())
        self.is_test = is_test
        self.max_iters = int(max_iters or 0)
        self.strict_truncation = bool(strict_truncation)

    @contextlib.contextmanager
    def block(self):
        parent = self.program.current_block()
        with _sub_block(self.program) as sub:
            yield
        # carried vars (written parent state incl. cond) need initial
        # values, so they are inputs too; append_while_op validates that
        # the body updates the condition
        append_while_op(parent, sub, self.cond_var.name, self.is_test,
                        self.max_iters,
                        strict_truncation=self.strict_truncation)


def while_loop(cond, body, loop_vars, is_test=False, name=None,
               max_iters: int = 0, strict_truncation: bool = False):
    """Functional while (reference layers/control_flow.py while_loop):
    `cond(*loop_vars) -> bool scalar var`, `body(*loop_vars) -> new vars`;
    returns the final loop vars.

        i, s = while_loop(lambda i, s: layers.less_than(i, n),
                          lambda i, s: (layers.increment(i, in_place=False),
                                        layers.elementwise_add(s, x)),
                          [i0, s0], max_iters=16)

    With max_iters > 0 the loop lowers to a masked lax.scan and is
    reverse-differentiable — append_backward trains straight through it
    (the reference's WhileGradOp capability, while_op.cc:167, rebuilt
    without the per-iteration scope tape).
    """
    if not loop_vars:
        raise ValueError("while_loop needs at least one loop var")
    if not callable(cond) or not callable(body):
        raise TypeError("while_loop cond and body must be callable")
    from . import layers
    init_cond = cond(*loop_vars)
    if init_cond.dtype != "bool":
        raise TypeError("while_loop cond must return a bool scalar var, "
                        f"got {init_cond.dtype}")
    w = While(init_cond, is_test=is_test, name=name, max_iters=max_iters,
              strict_truncation=strict_truncation)
    with w.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        if len(new_vars) != len(loop_vars):
            raise ValueError(
                f"while_loop body returned {len(new_vars)} vars, expected "
                f"{len(loop_vars)}")
        for new, old in zip(new_vars, loop_vars):
            if new is not old:
                layers.assign(new, output=old)
        next_cond = cond(*loop_vars)
        layers.assign(next_cond, output=init_cond)
    return list(loop_vars)


# ---------------------------------------------------------------------------
# cond / case / switch_case
# ---------------------------------------------------------------------------
def _flatten_rets(ret):
    if ret is None:
        return [], None
    if isinstance(ret, (list, tuple)):
        return list(ret), type(ret)
    return [ret], "single"


def cond(pred: VarDesc, true_fn=None, false_fn=None, name=None):
    """Two-branch conditional (control_flow.py:1976) lowered to
    jax.lax.cond.  Both branches must return the same structure of
    same-shape/dtype variables; writes to enclosing-scope variables inside a
    branch are merged (the other branch keeps the incoming value)."""
    program = (pred.block.program if pred.block is not None
               else default_main_program())
    parent = program.current_block()

    with _sub_block(program) as tb:
        t_ret = true_fn() if true_fn is not None else None
    with _sub_block(program) as fb:
        f_ret = false_fn() if false_fn is not None else None

    t_list, t_kind = _flatten_rets(t_ret)
    f_list, f_kind = _flatten_rets(f_ret)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches return different arity: true_fn -> "
            f"{len(t_list)} values, false_fn -> {len(f_list)}")

    t_free, t_written = _analyze_block(tb)
    f_free, f_written = _analyze_block(fb)
    # parent vars written by either branch are extra (merged) outputs
    extra = [n for n in dict.fromkeys(t_written + f_written)]
    free = list(dict.fromkeys(t_free + f_free + extra))
    free = [n for n in free if n != pred.name]

    true_outs = [v.name for v in t_list] + extra
    false_outs = [v.name for v in f_list] + extra

    out_vars = []
    for tv in t_list:
        ov = parent.create_var(name=unique_name("cond_out"),
                               shape=tv.shape, dtype=tv.dtype,
                               stop_gradient=tv.stop_gradient)
        out_vars.append(ov)
    out_names = [v.name for v in out_vars] + extra

    parent.append_op(
        "cond",
        inputs={"Cond": [pred.name], "Input": free},
        outputs={"Out": out_names},
        attrs={"true_block": tb.idx, "false_block": fb.idx,
               "input_names": free, "true_outs": true_outs,
               "false_outs": false_outs, "cond_name": pred.name})

    if t_kind is None:
        return None
    if t_kind == "single":
        return out_vars[0]
    return t_kind(out_vars)


def case(pred_fn_pairs, default=None, name=None):
    """if/elif/else chain (control_flow.py:2753) built from nested cond."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not rest:
        if default is None:
            # reference: last fn doubles as the default when none is given
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index: VarDesc, branch_fns, default=None, name=None):
    """Indexed dispatch (control_flow.py:3331).  branch_fns: dict
    {index: fn} or list of (index, fn) / fns."""
    from . import layers
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = [(i, f) if not isinstance(f, (tuple, list)) else tuple(f)
                 for i, f in enumerate(branch_fns)]
        items = [it if isinstance(it[0], int) else (i, it[1])
                 for i, it in enumerate(items)]
    if default is None:
        default = items[-1][1]
    pairs = []
    for idx, fn in items:
        idx_c = layers.fill_constant([1], branch_index.dtype, idx)
        pairs.append((layers.equal(branch_index, idx_c), fn))
    return case(pairs, default)


# ---------------------------------------------------------------------------
# Switch (first-true-wins assignment chain; LR-schedule workhorse)
# ---------------------------------------------------------------------------
class Switch:
    """control_flow.py:1461 `Switch`: sequential cases, first true wins;
    case bodies assign enclosing-scope variables.

        with layers.Switch() as switch:
            with switch.case(step < warmup):
                layers.assign(warm_lr, lr)
            with switch.default():
                layers.assign(base_lr, lr)

    Lowering: each case becomes a conditional_block op whose effective
    predicate is `cond_i AND NOT any(cond_j, j<i)`; the kernel computes the
    body unconditionally and merges with where(pred, new, old) — XLA select
    semantics, one fused computation, no host branching.
    """

    def __init__(self, name=None):
        self.program = default_main_program()
        self._prior = None  # var: OR of all previous case conditions
        self._has_default = False

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    @contextlib.contextmanager
    def case(self, condition: VarDesc):
        from . import layers
        if self._has_default:
            raise ValueError("Switch: case() after default()")
        # effective predicate, built in the PARENT block
        if self._prior is None:
            eff = condition
            self._prior = condition
        else:
            eff = layers.logical_and(condition,
                                     layers.logical_not(self._prior))
            self._prior = layers.logical_or(self._prior, condition)
        yield from self._guarded_block(eff)

    @contextlib.contextmanager
    def default(self):
        from . import layers
        if self._prior is None:
            raise ValueError("Switch: default() before any case()")
        self._has_default = True
        eff = layers.logical_not(self._prior)
        yield from self._guarded_block(eff)

    def _guarded_block(self, eff: VarDesc):
        parent = self.program.current_block()
        with _sub_block(self.program) as sub:
            yield
        free, written = _analyze_block(sub)
        if not written:
            raise ValueError("Switch case body assigns no enclosing-scope "
                             "variable — nothing to merge")
        # incoming values of written vars are needed for the merge
        inputs = list(dict.fromkeys(free + written))
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [eff.name], "Input": inputs},
            outputs={"Out": list(written)},
            attrs={"sub_block": sub.idx, "input_names": inputs,
                   "out_names": list(written)})


# ---------------------------------------------------------------------------
# StaticRNN -> lax.scan
# ---------------------------------------------------------------------------
class StaticRNN:
    """Recurrent network over a fixed-length (time-major) sequence
    (control_flow.py:411 `StaticRNN`, C++ operators/recurrent_op.cc).

        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [T, B, D] time-major
            h_prev = rnn.memory(init=h0)     # h0: [B, H]
            h = layers.fc(layers.concat([x_t, h_prev], 1), H, act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        hs = rnn()                           # [T, B, H]

    Lowered to ONE `static_rnn` op executed as jax.lax.scan: compiled
    recurrence, constant graph size in T, reverse-differentiable (so
    training works through it — unlike `While`).
    """

    def __init__(self, name=None):
        self.program = default_main_program()
        self._sub: Optional[Block] = None
        self._scan_inputs: List[Tuple[str, str]] = []  # (parent, in-block)
        self._memories: List[Tuple[str, str, Optional[str]]] = []
        self._step_outputs: List[str] = []
        self._seq_len: Optional[int] = None
        self._status = "before"
        self._out_vars: List[VarDesc] = []

    @contextlib.contextmanager
    def step(self):
        parent = self.program.current_block()
        self._status = "in"
        with _sub_block(self.program) as sub:
            self._sub = sub
            yield
        self._status = "after"
        self._finalize(parent)

    def _require_in_step(self):
        if self._status != "in":
            raise RuntimeError("StaticRNN: call inside `with rnn.step():`")

    def step_input(self, x: VarDesc) -> VarDesc:
        self._require_in_step()
        if x.shape is None or len(x.shape) < 1:
            raise ValueError("step_input needs a [T, ...] time-major var")
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        xt = self._sub.create_var(name=unique_name(x.name + "@step"),
                                  shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._scan_inputs.append((x.name, xt.name))
        return xt

    def memory(self, init: Optional[VarDesc] = None, shape=None,
               batch_ref: Optional[VarDesc] = None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1) -> VarDesc:
        self._require_in_step()
        from . import layers
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("StaticRNN.memory needs init= or "
                                 "(shape=, batch_ref=)")
            # boot var built in the PARENT block (reference parity)
            cur = self.program._current_block_idx
            self.program._current_block_idx = self._sub.parent_idx
            try:
                init = layers.fill_constant_batch_size_like(
                    batch_ref, [-1] + list(shape[1:] if len(shape) > 1
                                           else shape),
                    "float32", init_value,
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=init_batch_dim_idx)
            finally:
                self.program._current_block_idx = cur
        pre = self._sub.create_var(name=unique_name(init.name + "@pre"),
                                   shape=init.shape, dtype=init.dtype)
        self._memories.append([init.name, pre.name, None])
        return pre

    def update_memory(self, mem: VarDesc, var: VarDesc):
        self._require_in_step()
        for m in self._memories:
            if m[1] == mem.name:
                m[2] = var.name
                return
        raise ValueError(f"{mem.name!r} is not a StaticRNN memory")

    def step_output(self, o: VarDesc):
        self._require_in_step()
        self._step_outputs.append(o.name)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self, parent: Block):
        if not self._step_outputs:
            raise ValueError("StaticRNN produced no step_output")
        for boot, pre, upd in self._memories:
            if upd is None:
                raise ValueError(f"memory {pre!r} never update_memory()d")
        free, _ = _analyze_block(self._sub)
        local = ({p for _, p in self._scan_inputs}
                 | {pre for _, pre, _ in self._memories})
        x_names = list(dict.fromkeys(
            [n for n in free if n not in local]
            + [pn for pn, _ in self._scan_inputs]
            + [boot for boot, _, _ in self._memories]))
        self._out_vars = []
        for n in self._step_outputs:
            v = self._sub.var(n)
            shape = ((self._seq_len,) + tuple(v.shape)
                     if v.shape is not None and self._seq_len is not None
                     else None)
            self._out_vars.append(parent.create_var(
                name=unique_name("rnn_out"), shape=shape, dtype=v.dtype))
        parent.append_op(
            "static_rnn",
            inputs={"X": x_names},
            outputs={"Out": [v.name for v in self._out_vars]},
            attrs={"sub_block": self._sub.idx, "x_names": x_names,
                   "scan_inputs": [list(p) for p in self._scan_inputs],
                   "memories": [list(m) for m in self._memories],
                   "step_outputs": list(self._step_outputs)})

    def __call__(self):
        if self._status != "after":
            raise RuntimeError("StaticRNN outputs available after the "
                               "step() block closes")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return list(self._out_vars)


# ---------------------------------------------------------------------------
# DynamicRNN -> masked lax.scan
# ---------------------------------------------------------------------------
class DynamicRNN:
    """Variable-length recurrence (control_flow.py:2938 `DynamicRNN`).

    The reference unfolds a LoD minibatch with a While loop over
    lod_tensor_to_array slices, sorting sequences by length and shrinking
    the batch as short sequences finish.  TPU redesign: sequences arrive
    padded [B, T, ...] with an explicit lengths vector (io/bucketing.py —
    the LoD replacement), the whole recurrence lowers to ONE `dynamic_rnn`
    op (a masked lax.scan, see ops/kernels/control.py), and `step < len`
    masking replaces batch shrinking: memories freeze at each sequence's
    last real step, outputs are zero beyond it.  No sorting happens, so
    rows keep their input order and `memory(need_reorder=True)` is
    accepted as a no-op.

        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(emb, length=seq_lens)  # emb [B,T,D]
            enc  = drnn.static_input(encoder_proj)        # visible as-is
            mem  = drnn.memory(shape=[H])                 # zeros [B,H]
            h = layers.fc(layers.concat([word, mem], 1), H, act="tanh")
            drnn.update_memory(mem, h)
            drnn.output(h)
        out = drnn()                                      # [B, T, H]
        last = layers.sequence_last_step(out, length=seq_lens)
    """

    BEFORE_RNN, IN_RNN, AFTER_RNN = 0, 1, 2

    def __init__(self, name=None):
        self.program = default_main_program()
        self.status = DynamicRNN.BEFORE_RNN
        self._sub: Optional[Block] = None
        self._lengths_name: Optional[str] = None
        self._batch_ref: Optional[VarDesc] = None
        self._seq_len: Optional[int] = None
        self._scan_inputs: List[Tuple[str, str]] = []
        self._memories: List[List[Optional[str]]] = []
        self._step_outputs: List[str] = []
        self._out_vars: List[VarDesc] = []

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("rnn.block() can only be invoked once")
        parent = self.program.current_block()
        self.status = DynamicRNN.IN_RNN
        with _sub_block(self.program) as sub:
            self._sub = sub
            yield
        self.status = DynamicRNN.AFTER_RNN
        self._finalize(parent)

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(
                f"{method}() can only be called inside `with drnn.block():`")

    def step_input(self, x: VarDesc, level=0, length: VarDesc = None):
        """Set padded sequence x [B, T, ...] as a per-step input; returns
        the [B, ...] time slice inside the block.  The first call must
        pass `length` (int vector [B] of true sequence lengths) — the
        explicit replacement for the LoD the reference reads off x."""
        self._assert_in_rnn_block_("step_input")
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("DynamicRNN.step_input needs a padded "
                             "[batch, time, ...] variable")
        if self._lengths_name is None:
            if length is None:
                raise ValueError(
                    "DynamicRNN.step_input: the first step input must "
                    "pass length= (int [batch] true lengths) — padded "
                    "tensors carry no LoD here (io/bucketing.py)")
            self._lengths_name = length.name
            self._batch_ref = x
            self._seq_len = x.shape[1]
        else:
            if length is not None and length.name != self._lengths_name:
                raise ValueError(
                    "DynamicRNN.step_input: conflicting length= "
                    f"({length.name!r} vs {self._lengths_name!r}) — all "
                    "step inputs share the first call's lengths")
            if (self._seq_len is not None and x.shape[1] is not None
                    and x.shape[1] != self._seq_len):
                raise ValueError(
                    f"DynamicRNN.step_input: {x.name!r} has time length "
                    f"{x.shape[1]} but the first step input has "
                    f"{self._seq_len} — padded step inputs must share "
                    "one time axis")
        xt = self._sub.create_var(
            name=unique_name(x.name + "@step"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._scan_inputs.append((x.name, xt.name))
        return xt

    def static_input(self, x: VarDesc) -> VarDesc:
        """Reference reorders x into rank order and shrinks it per step
        (control_flow.py:3157).  With no sorting and no shrinking both
        transforms are identities, so the variable is visible in the block
        unchanged."""
        self._assert_in_rnn_block_("static_input")
        if self._lengths_name is None:
            raise RuntimeError(
                "static_input() must be called after step_input().")
        return x

    def memory(self, init: Optional[VarDesc] = None, shape=None, value=0.0,
               need_reorder=False, dtype="float32"):
        self._assert_in_rnn_block_("memory")
        if self._lengths_name is None:
            raise ValueError(
                "memory() can only be called after step_input().")
        from . import layers
        if init is None:
            if shape is None:
                raise ValueError("DynamicRNN.memory needs init= or shape=")
            # boot built in the PARENT block: zeros [B, *shape] batched
            # like the step input (reference parity:
            # fill_constant_batch_size_like against the rank table)
            cur = self.program._current_block_idx
            self.program._current_block_idx = self._sub.parent_idx
            try:
                init = layers.fill_constant_batch_size_like(
                    self._batch_ref, [-1] + list(shape), dtype, value,
                    input_dim_idx=0, output_dim_idx=0)
            finally:
                self.program._current_block_idx = cur
        # need_reorder reorders the boot into rank order in the reference;
        # rows are never permuted here, so it is correct as a no-op
        pre = self._sub.create_var(name=unique_name(init.name + "@pre"),
                                   shape=init.shape, dtype=init.dtype)
        self._memories.append([init.name, pre.name, None])
        return pre

    def update_memory(self, ex_mem: VarDesc, new_mem: VarDesc):
        self._assert_in_rnn_block_("update_memory")
        for m in self._memories:
            if m[1] == ex_mem.name:
                m[2] = new_mem.name
                return
        raise ValueError(f"{ex_mem.name!r} is not a DynamicRNN memory")

    def output(self, *outputs):
        self._assert_in_rnn_block_("output")
        for o in outputs:
            self._step_outputs.append(o.name)

    def _finalize(self, parent: Block):
        if self._lengths_name is None:
            raise ValueError("DynamicRNN block defined no step_input")
        if not self._step_outputs:
            raise ValueError("DynamicRNN produced no output()")
        for boot, pre, upd in self._memories:
            if upd is None:
                raise ValueError(f"memory {pre!r} never update_memory()d")
        free, _ = _analyze_block(self._sub)
        local = ({inb for _, inb in self._scan_inputs}
                 | {pre for _, pre, _ in self._memories})
        x_names = list(dict.fromkeys(
            [n for n in free if n not in local]
            + [pn for pn, _ in self._scan_inputs]
            + [boot for boot, _, _ in self._memories]
            + [self._lengths_name]))
        self._out_vars = []
        batch = self._batch_ref.shape[0]
        for n in self._step_outputs:
            v = self._sub.var(n)
            shape = ((batch, self._seq_len) + tuple(v.shape[1:])
                     if v.shape is not None else None)
            self._out_vars.append(parent.create_var(
                name=unique_name("dynamic_rnn_out"), shape=shape,
                dtype=v.dtype))
        parent.append_op(
            "dynamic_rnn",
            inputs={"X": x_names},
            outputs={"Out": [v.name for v in self._out_vars]},
            attrs={"sub_block": self._sub.idx, "x_names": x_names,
                   "scan_inputs": [list(p) for p in self._scan_inputs],
                   "memories": [list(m) for m in self._memories],
                   "step_outputs": list(self._step_outputs),
                   "lengths_name": self._lengths_name})

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("Output of the dynamic RNN can only be "
                             "visited outside the rnn block.")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return list(self._out_vars)


# ---------------------------------------------------------------------------
# LoD rank-table plumbing (ops in ops/kernels/lod_array.py)
# ---------------------------------------------------------------------------
def lod_rank_table(x: VarDesc = None, level=0, length: VarDesc = None):
    """control_flow.py lod_rank_table — dense [2, B] rank table (sorted
    indices + lengths).  `length` is required: the explicit lengths vector
    replaces the LoD the reference reads off x."""
    if length is None:
        raise ValueError("lod_rank_table needs length= (int [batch]); "
                         "padded tensors carry no LoD")
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int32", True)
    ins = {"Length": [length.name]}
    if x is not None:
        ins["X"] = [x.name]
    helper.append_op("lod_rank_table", inputs=ins,
                     outputs={"Out": [out]}, attrs={"level": level})
    return out


def max_sequence_len(rank_table: VarDesc):
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("max_sequence_len",
                     inputs={"RankTable": [rank_table.name]},
                     outputs={"Out": [out]})
    return out


def lod_tensor_to_array(x: VarDesc, table: VarDesc):
    """Padded [B, T, ...] -> time-major tensor array in rank order."""
    helper = LayerHelper("lod_tensor_to_array")
    out = helper.create_variable_for_type_inference(x.dtype)
    out.attrs["is_tensor_array"] = True
    # remember the padded source shape so array_to_lod_tensor can restore
    # a static shape for its consumers (fc etc.)
    if x.shape is not None:
        out.attrs["lod_src_shape"] = list(x.shape)
    helper.append_op("lod_tensor_to_array",
                     inputs={"X": [x.name], "RankTable": [table.name]},
                     outputs={"Out": [out]})
    return out


def array_to_lod_tensor(x: VarDesc, table: VarDesc):
    """Inverse of lod_tensor_to_array: back to [B, T, ...], input order."""
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(x.dtype)
    src_shape = x.attrs.get("lod_src_shape")
    if src_shape is not None:
        out.shape = tuple(src_shape)
    helper.append_op("array_to_lod_tensor",
                     inputs={"X": [x.name], "RankTable": [table.name]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x: VarDesc, rank_table: VarDesc):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("reorder_lod_tensor_by_rank",
                     inputs={"X": [x.name], "RankTable": [rank_table.name]},
                     outputs={"Out": [out]})
    return out


def shrink_memory(x: VarDesc, i: VarDesc, table: VarDesc):
    """Identity on TPU (masking replaces shrinking) — see
    ops/kernels/lod_array.py shrink_rnn_memory."""
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("shrink_rnn_memory",
                     inputs={"X": [x.name], "I": [i.name],
                             "RankTable": [table.name]},
                     outputs={"Out": [out]})
    return out


def split_lod_tensor(input: VarDesc, mask: VarDesc, level=0):
    """Row-route input by bool mask into (true, false) full-shape tensors
    with unselected rows zeroed (split_lod_tensor_op.cc, masked-select
    redesign)."""
    helper = LayerHelper("split_lod_tensor")
    out_true = helper.create_variable_for_type_inference(input.dtype)
    out_false = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("split_lod_tensor",
                     inputs={"X": [input.name], "Mask": [mask.name]},
                     outputs={"OutTrue": [out_true],
                              "OutFalse": [out_false]},
                     attrs={"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true: VarDesc, in_false: VarDesc, x: VarDesc,
                     mask: VarDesc, level=0):
    helper = LayerHelper("merge_lod_tensor")
    out = helper.create_variable_for_type_inference(in_true.dtype)
    ins = {"Mask": [mask.name], "InTrue": [in_true.name],
           "InFalse": [in_false.name]}
    if x is not None:
        ins["X"] = [x.name]
    helper.append_op("merge_lod_tensor", inputs=ins,
                     outputs={"Out": [out]}, attrs={"level": level})
    return out


# ---------------------------------------------------------------------------
# tensor array (LoDTensorArray analog: fixed-capacity device buffer)
# ---------------------------------------------------------------------------
def create_array(dtype, initialized_list=None):
    """LoDTensorArray analog (layers/tensor.py create_array).  On TPU the
    array is a fixed-capacity device buffer (see ops/kernels/tensor_array.py
    TensorArrayVal); capacity is taken at the first array_write."""
    helper = LayerHelper("create_array")
    out = helper.create_variable_for_type_inference(dtype, True)
    out.attrs["is_tensor_array"] = True
    helper.append_op("create_tensor_array", outputs={"Out": [out]},
                     attrs={"dtype": out.dtype})
    if initialized_list:
        from . import layers
        i = layers.fill_constant([1], "int64", 0)
        for x in initialized_list:
            array_write(x, i, array=out)
            i = layers.increment(i, in_place=False)
    return out


def array_write(x: VarDesc, i: VarDesc, array=None, max_len=None):
    """write x at index i (tensor_array_read_write ops).  max_len bounds the
    buffer capacity when the array is empty (default from
    FLAGS_tensor_array_max_len, 256)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    helper.append_op("write_to_array",
                     inputs={"X": [x], "I": [i], "Array": [array]},
                     outputs={"Out": [array]},
                     attrs={"max_len": max_len or 0})
    return array


def array_read(array: VarDesc, i: VarDesc):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op("read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array: VarDesc):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64", True)
    helper.append_op("lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out

"""Parameter initializers — append init ops to the startup program.

Analog of /root/reference/python/paddle/fluid/initializer.py (Constant :118,
Uniform :214, Normal :308, Xavier :438, MSRA :557, Bilinear, NumpyArrayInit).
Each initializer appends one op (fill_constant / uniform_random /
gaussian_random / assign_value) to the *startup* program's global block; the
Executor runs the startup program once to materialise parameters, exactly like
the reference's two-program contract.
"""
from __future__ import annotations

import math

import numpy as np

from ..core.program import default_startup_program, VarDesc

__all__ = [
    "Initializer", "Constant", "ConstantInitializer", "Uniform",
    "UniformInitializer", "Normal", "NormalInitializer", "TruncatedNormal",
    "TruncatedNormalInitializer", "Xavier", "XavierInitializer", "MSRA",
    "MSRAInitializer", "NumpyArrayInitializer", "Assign",
    "Bilinear", "BilinearInitializer",
    "_global_weight_initializer", "_global_bias_initializer",
    "set_global_initializer",
]


class Initializer:
    """Base: __call__(var, block) appends the init op into `block` (normally
    the startup program's global block)."""

    def __call__(self, var: VarDesc, block=None):
        raise NotImplementedError

    def _startup_block(self, block):
        if block is not None:
            return block
        return default_startup_program().global_block()

    def _declare(self, block, var):
        # the startup program needs its own VarDesc for the parameter
        if var.name not in block.vars:
            block.vars[var.name] = VarDesc(
                var.name, var.shape, var.dtype, persistable=True,
                is_parameter=var.is_parameter, block=block)


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1,) * 2
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # fluid convention: fan_in from dim0 for fc ([in, out]), conv is
    # [out_c, in_c, k, k]
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = float(value)

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        block.append_op(
            "fill_constant", outputs={"Out": var.name},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": self.value})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = float(low), float(high), seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        block.append_op(
            "uniform_random", outputs={"Out": var.name},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = float(loc), float(scale), seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        block.append_op(
            "gaussian_random", outputs={"Out": var.name},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = float(loc), float(scale), seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        block.append_op(
            "truncated_gaussian_random", outputs={"Out": var.name},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed})


class XavierInitializer(Initializer):
    """Glorot (fluid/initializer.py:438): uniform or normal scaled by
    fan_in+fan_out."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            block.append_op(
                "uniform_random", outputs={"Out": var.name},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "min": -limit, "max": limit, "seed": self.seed})
        else:
            std = math.sqrt(2.0 / (fi + fo))
            block.append_op(
                "gaussian_random", outputs={"Out": var.name},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "mean": 0.0, "std": std, "seed": self.seed})


class MSRAInitializer(Initializer):
    """Kaiming/He init (fluid/initializer.py:557)."""

    def __init__(self, uniform=True, fan_in=None, seed=0,
                 negative_slope=0.0, nonlinearity="relu"):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            block.append_op(
                "uniform_random", outputs={"Out": var.name},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "min": -limit, "max": limit, "seed": self.seed})
        else:
            std = math.sqrt(2.0 / fi)
            block.append_op(
                "gaussian_random", outputs={"Out": var.name},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "mean": 0.0, "std": std, "seed": self.seed})


class BilinearInitializer(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    fluid/initializer.py BilinearInitializer): a [C, c, k, k] filter
    whose spatial kernel is the separable triangle
    (1-|x/f - c|)(1-|y/f - c|), so conv_transpose with stride=factor
    performs bilinear interpolation."""

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        if shape[2] != shape[3]:
            raise ValueError("kernel must be square (shape[2]==shape[3])")
        size = shape[3]
        f = np.ceil(size / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        n = int(np.prod(shape))
        idx = np.arange(n)
        x = idx % size
        y = (idx // size) % size
        weight = ((1 - np.abs(x / f - c))
                  * (1 - np.abs(y / f - c))).astype(np.float32)
        block.append_op(
            "assign_value", outputs={"Out": var.name},
            attrs={"shape": list(shape), "dtype": var.dtype,
                   "values": weight.tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block=None):
        block = self._startup_block(block)
        self._declare(block, var)
        block.append_op(
            "assign_value", outputs={"Out": var.name},
            attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                   "values": self.value.ravel().tolist()})


# fluid-style aliases
Bilinear = BilinearInitializer
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Assign = NumpyArrayInitializer

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_weight_initializer():
    return _global_weight_init


def _global_bias_initializer():
    return _global_bias_init

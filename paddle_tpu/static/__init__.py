"""paddle_tpu.static — the static-graph (fluid) API surface.

Analog of `paddle.fluid` / `paddle.static`: Program construction, layers,
Executor, backward, optimizers, initializers (SURVEY.md §2.2 P1-P6).
"""
from ..core.program import (  # noqa: F401
    device_guard,
    Program, Block, OpDesc, VarDesc, OpRole, default_main_program,
    default_startup_program, program_guard, name_scope, unique_name,
)
from ..core.place import (  # noqa: F401
    CPUPlace, XLAPlace, TPUPlace, CUDAPlace,
)
from .executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard, BlockTracer,
)
from .backward import append_backward, gradients  # noqa: F401
from .memory_analysis import (  # noqa: F401
    estimate_peak_bytes, analyze_program, hbm_budget_bytes,
    select_layer_checkpoints,
)
from .optimizer import gradient_merge  # noqa: F401
from . import memory_analysis  # noqa: F401
from .flops_analysis import (  # noqa: F401
    analyze_flops, estimate_step_flops, peak_flops_per_chip,
)
from . import flops_analysis  # noqa: F401
from .verifier import (  # noqa: F401
    check_program, collective_sequence, collective_wire_bytes,
    collective_wire_bytes_by_axis, program_ring_degrees,
    VerifyReport, Diagnostic, ProgramVerificationError,
)
from . import verifier  # noqa: F401
from .layout_analysis import (  # noqa: F401
    propagate_shardings, ShardingLayout, LayoutSpec,
)
from . import layout_analysis  # noqa: F401
from .planner import (  # noqa: F401
    plan_program, apply_plan, Plan, ici_bytes_per_chip, page_budget,
    calibrate, Calibration, default_calibration,
)
from . import planner  # noqa: F401
from .recompute_rewrite import apply_recompute  # noqa: F401
from .initializer import (  # noqa: F401
    Constant, Uniform, Normal, TruncatedNormal, Xavier, MSRA,
    NumpyArrayInitializer, set_global_initializer,
)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import nets  # noqa: F401
from . import control_flow  # noqa: F401
from .layers import data  # noqa: F401

from .optimizer import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta, RMSProp, Ftrl,
    Lamb, ExponentialMovingAverage, L1Decay, L2Decay, GradientClipByValue,
    GradientClipByNorm, GradientClipByGlobalNorm,
    SGDOptimizer, MomentumOptimizer, AdamOptimizer, AdamaxOptimizer,
    AdagradOptimizer, AdadeltaOptimizer, RMSPropOptimizer, FtrlOptimizer,
    DecayedAdagradOptimizer, DpsgdOptimizer, LambOptimizer,
    LarsMomentumOptimizer, ModelAverage, LookaheadOptimizer,
    RecomputeOptimizer,
)
from .optimizer import DecayedAdagrad, Dpsgd  # noqa: F401
from .layers import Print, py_func  # noqa: F401
from ..jit import InputSpec  # noqa: F401

from ..io.framework_io import (  # noqa: F401
    save_vars, save_params, save_persistables, load_vars, load_params,
    load_persistables, save_inference_model, load_inference_model,
    set_program_state, load_program_state,
)
from ..io.framework_io import static_save as save  # noqa: F401
from ..io.framework_io import static_load as load  # noqa: F401
from ..distributed.compiled_program import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy,
)
class ParallelExecutor:
    """Fluid ParallelExecutor constructor compatibility
    (framework.ParallelExecutor(use_cuda, loss_name=..., ...)): wraps
    CompiledProgram.with_data_parallel over all local devices; run via
    Executor.run(pe, ...) or pe.run(fetch_list, feed)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from ..core.program import default_main_program
        program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            program, build_strategy=build_strategy).with_data_parallel(
            loss_name=loss_name, exec_strategy=exec_strategy,
            share_vars_from=getattr(share_vars_from, "_compiled",
                                    share_vars_from))
        self._scope = scope

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        return self._compiled._run(executor, feed, fetch_list,
                                   scope or self._scope, return_numpy)

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        from .executor import Executor
        return Executor().run(self, feed=feed or feed_dict,
                              fetch_list=fetch_list,
                              return_numpy=return_numpy)

"""Compile-time HBM accounting: predict a program's peak device memory
WITHOUT running it on the chip.

Motivation (docs/perf.md round 5): the framework is memory-bound, not
dispatch-bound — b64 hits the MFU north star while b96 misses HBM by
274 MB — and until now the only way to learn a config's HBM fate was to
burn a rare tunnel window on it.  This module answers fits-or-OOMs at
program-build time:

  * `estimate_peak_bytes(program, batch=...)` — an op-IR liveness walker
    over the Program: var sizes from shape×dtype (symbolic -1 batch dims
    bound to `batch`), a forward+backward live-set sweep over the op
    list, per-phase (forward / backward / optimize) peaks.
  * `Executor.memory_report(program, feed)` — the estimate plus XLA
    ground truth via ``jit(step).lower(...).compile().memory_analysis()``
    where the installed backend supports it (static/executor.py).
  * `PADDLE_TPU_HBM_BYTES` — the per-chip budget the fits/OOM verdict is
    judged against.  Default: v5e usable HBM, 15.75 GiB — the allocation
    ceiling the round-5 OOMs reported (16 GiB card minus the XLA
    reserve), so "predicted OOM" means the same thing the chip's
    allocator error does.

The walker models the three XLA behaviours that dominate the gap between
"sum of every var ever created" and the real footprint; each is a
module-level table so the model stays inspectable and tunable:

  * `_ALIAS_OPS` — pure layout ops (reshape/squeeze/...) alias their
    input buffer: zero cost.
  * `_FUSABLE_OPS` — cheap elementwise ops (cast/scale/gelu/transpose/
    add/...) are fused into their consumers by XLA and rematerialized
    for free in backward, so their outputs never occupy standalone HBM;
    their *inputs* stay live instead (the sweep keeps them live because
    the grad ops reference them).
  * `_GRAD_RELEASED_INPUTS` — grad ops formally reference every forward
    input/output (registry slot convention), but under whole-block jit
    the auto-vjp's forward replay is CSE'd with the original forward, so
    the real residual set is smaller: softmax backward needs only its
    OUTPUT (the pre-softmax logits die at the softmax), cross-entropy
    backward needs the saved softmax, not the logits, dropout recomputes
    its mask from the counter PRNG.  Uses listed here do not extend a
    var's live range into the backward sweep.

Remat composes for free: `recompute_rewrite` produces a program whose
backward replays segments through `optimization_barrier` + @RC aliases,
so the same sweep over the rewritten op list shows the reduced peak —
no special-casing.

`select_layer_checkpoints` picks remat checkpoint vars at transformer
LAYER boundaries (the same boundaries a user hands RecomputeOptimizer):
for each attention core op (softmax over scores / flash_attention /
ring_attention / multihead_matmul) it walks back to the nearest
preceding layer_norm output — one checkpoint per layer, at the layer's
entry.  `FLAGS_recompute=auto` (static/backward.py) uses this selection
and applies the rewrite only when the estimator predicts the budget is
exceeded; `FLAGS_recompute=always` applies it unconditionally.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Set

import numpy as np

from ..core.program import Program, OpRole

__all__ = ["estimate_peak_bytes", "analyze_program", "hbm_budget_bytes",
           "select_layer_checkpoints", "mp_sharded_vars",
           "DEFAULT_HBM_BYTES"]

# v5e usable HBM: the 16 GiB card minus the XLA runtime reserve — the
# ceiling the round-5 allocator errors quoted ("15.75G of 16.00G").
DEFAULT_HBM_BYTES = int(15.75 * 2 ** 30)

HBM_BUDGET_ENV = "PADDLE_TPU_HBM_BYTES"

# The walker deliberately does NOT model XLA's own HLO rematerialization
# pass, which kicks in under memory pressure and recomputes cheap
# fusions (attention probs, activation chains) to squeeze a program
# under the limit.  Calibration against the r5 chip measurements: BERT-
# base b64 walks to 17.1 GiB yet ran within the 15.75 GiB ceiling
# (~9% recovered), while b96 (24.9 GiB walked, 58% over) OOM'd — XLA
# remat recovers a thin margin, not a multiple.  The fits verdict grants
# that calibrated slack; the raw walked peak is always reported
# alongside so the verdict's provenance stays visible.
XLA_REMAT_SLACK = 1.10


def hbm_budget_bytes() -> int:
    """Per-chip HBM budget the fits/OOM verdict is judged against
    (``PADDLE_TPU_HBM_BYTES`` env; default v5e usable 15.75 GiB)."""
    raw = os.environ.get(HBM_BUDGET_ENV, "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    return DEFAULT_HBM_BYTES


# pure layout / view ops: output aliases the input buffer (zero HBM
# cost; uses of the output count as uses of the input's root buffer)
_ALIAS_OPS = frozenset((
    "reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
    "unsqueeze2", "flatten", "flatten2", "flatten_contiguous_range",
    "assign", "share_data", "optimization_barrier",
))

# cheap (near-)elementwise ops XLA fuses into their consumers and freely
# rematerializes in backward: the output never occupies standalone HBM —
# a later use of it is a use of its ROOT buffer(s) instead (rep
# propagation).  Binary arithmetic (add/mul/...) is deliberately NOT
# here: its output is a genuinely new value that XLA materializes.
_FUSABLE_OPS = frozenset((
    "cast", "scale", "transpose", "transpose2", "fill_constant",
    "fill_any_like", "fill_zeros_like",
    "gelu", "relu", "relu6", "sigmoid", "tanh", "dropout",
    "sqrt", "rsqrt", "square", "abs", "exp", "log", "clip",
    "increment",
))

# (grad op type, input slot) pairs whose formal dependency the real vjp
# never materializes (residual-set model; see module docstring).  A use
# listed here does not extend the var's live range.
_GRAD_RELEASED_INPUTS = frozenset((
    ("softmax_grad", "X"),                         # residual = Out
    ("softmax_with_cross_entropy_grad", "Logits"),  # residual = Softmax
    ("log_softmax_grad", "X"),                     # residual = Out
    ("dropout_grad", "X"),                         # mask replays from PRNG
    ("dropout_grad", "Out"),
    ("mean_grad", "X"),                            # vjp needs only shape
    # relu/gelu are _FUSABLE_OPS (cost-0 outputs); releasing the grad's
    # Out use stops the rep chain from pinning roots the vjp never
    # reads.  Do NOT also list them in _GRAD_KEPT_OUTPUTS — the release
    # table is checked first and owns these ops.
    ("relu_grad", "Out"),
    ("gelu_grad", "Out"),
    ("tanh_grad", "X"),                            # residual = Out
    ("sigmoid_grad", "X"),                         # residual = Out
    # pass-through gradients: d(add)/dX is the cotangent itself (plus a
    # shape-only broadcast reduce), so the operand VALUES are never read
    ("elementwise_add_grad", "X"),
    ("elementwise_add_grad", "Y"),
    ("elementwise_sub_grad", "X"),
    ("elementwise_sub_grad", "Y"),
    ("scale_grad", "X"),
    ("cast_grad", "X"),
    ("transpose2_grad", "X"),
    ("transpose_grad", "X"),
    ("reshape2_grad", "X"),
    ("reshape_grad", "X"),
    ("concat_grad", "X"),                          # slice of cotangent
    ("split_grad", "X"),
))

# Grad ops also reference every forward OUTPUT slot (registry slot
# convention), but almost no vjp reads the output VALUE — the default
# here is to release those uses.  Exceptions: ops whose vjp residual IS
# the output (y = f(x) with dy/dx expressible in y), listed as
# (forward op type, output slot) pairs that stay live into backward.
_GRAD_KEPT_OUTPUTS = frozenset((
    ("softmax", "Out"),
    ("log_softmax", "Out"),
    ("softmax_with_cross_entropy", "Softmax"),
    ("tanh", "Out"),
    ("sigmoid", "Out"),
    ("exp", "Out"),
    ("sqrt", "Out"),
    ("rsqrt", "Out"),
    ("layer_norm", "Mean"),
    ("layer_norm", "Variance"),
    ("batch_norm", "SavedMean"),
    ("batch_norm", "SavedVariance"),
    ("flash_attention", "Out"),      # custom bwd consumes out (+lse)
))


def _use_released(op_type: str, slot: str) -> bool:
    """True when this (grad op, input slot) use never materializes the
    var (residual-set model): explicit release table for forward-input
    slots, default-release for forward-output value slots."""
    if (op_type, slot) in _GRAD_RELEASED_INPUTS:
        return True
    if not op_type.endswith("_grad") or slot.endswith("@GRAD"):
        return False
    from ..ops.registry import get_op_info
    fwd_type = op_type[: -len("_grad")]
    finfo = get_op_info(fwd_type)
    if finfo is None:
        return False
    if any(s.name == slot for s in finfo.outputs):
        return (fwd_type, slot) not in _GRAD_KEPT_OUTPUTS
    return False

# attention-core op types that mark "one transformer layer" for
# checkpoint selection
_ATTENTION_CORE_OPS = ("flash_attention", "ring_attention",
                       "multihead_matmul")


def _op_internal_bytes(op, sizer) -> int:
    """HBM a kernel materializes INSIDE the op, invisible to the var-
    level walk.  ring_attention on a single device (no "sp" mesh axis)
    degrades to plain attention and materializes the full fp32 [B, H,
    S, S] scores, retained as the vjp residual — the walker must charge
    it or a single-chip long-seq 'fits' verdict is fiction.  Under a
    real sp mesh of degree n the true footprint is n² smaller, so this
    is the conservative (single-chip, the only hardware we have) bound;
    flash_attention's whole point is that it has no such tensor."""
    if op.type != "ring_attention":
        return 0
    q = op.inputs.get("Q", [])
    if not q or not q[0]:
        return 0
    # resolve @RCB/@RC replay aliases to the base var: the remat replay
    # of a ring op materializes the same degraded-kernel scores
    var = sizer.var_of(q[0])
    shape = var.shape if var is not None else None
    if shape is None or len(shape) < 2:
        return 0
    b = sizer.batch if shape[0] in (-1, None) else int(shape[0])
    s = sizer.batch if shape[1] in (-1, None) else int(shape[1])
    h = int(op.attrs.get("num_heads", 1))
    return b * h * s * s * 4  # fp32 score accumulation

# name suffixes minted by the backward/remat/AMP/sharding rewrites; a var
# whose shape was never inferred (grad pieces, @RC replay aliases) borrows
# the base var's shape/dtype by stripping these
_DERIVED_MARKERS = ("@GRAD", "@RC", "@RCB", "@SUM", "@MASKED",
                    "@UNSCALED", "@GUARDED", "@ALLREDUCE", "@SCALED",
                    "@GradientMerge", "@GM_AVG", "@ZERO",
                    "@Z1FLAT", "@Z1SEG")


def _strip_derived(name: str) -> Optional[str]:
    """``x@GRAD_3`` -> ``x``; None when the name has no derived marker."""
    base = name
    # unique_name suffix: trailing _<digits>
    head, _, tail = base.rpartition("_")
    if head and tail.isdigit():
        base = head
    hit = False
    while True:
        for mark in _DERIVED_MARKERS:
            if base.endswith(mark):
                base = base[: -len(mark)]
                hit = True
                break
        else:
            break
    return base if hit else None


class _Sizer:
    """name -> bytes, binding symbolic -1 dims to `batch` and resolving
    derived names (@GRAD/@RC/...) to their base var's shape/dtype.

    `tp_sharded`/`tp_degree`: vars the sharding-propagation analyzer
    proved mp-sharded are charged 1/degree per chip — each rank
    materializes only its feature shard (weights, their grads and
    residual activations between a column- and row-parallel layer).
    Derived names divide through their BASE var's verdict: the grad of
    a sharded weight is the same local shard."""

    def __init__(self, block, batch: int, tp_sharded=None,
                 tp_degree: int = 0):
        self.block = block
        self.batch = max(1, int(batch))
        self.tp_sharded = tp_sharded or frozenset()
        self.tp_degree = max(0, int(tp_degree))
        self.cache: Dict[str, int] = {}
        self.unknown: List[str] = []

    def var_of(self, name: str):
        """Resolve `name` to a shaped VarDesc, falling back to the base
        var for derived names (@GRAD/@RC/... aliases carry no shape)."""
        var = self.block.vars.get(name)
        if var is not None and var.shape is not None:
            return var
        base = _strip_derived(name)
        if base is not None and self.block.has_var(base):
            return self.block.var(base)
        return var

    def _var_bytes(self, var) -> Optional[int]:
        if var is None or var.shape is None or var.dtype is None:
            return None
        from ..core.dtype import np_dtype
        n = 1
        for d in var.shape:
            n *= self.batch if d in (-1, None) else int(d)
        try:
            return int(n) * np.dtype(np_dtype(var.dtype)).itemsize
        except (TypeError, ValueError):
            return None

    def __call__(self, name: str) -> int:
        if name in self.cache:
            return self.cache[name]
        var = self.var_of(name)
        size = self._var_bytes(var)
        if size is None:
            self.unknown.append(name)
            size = 0
        elif self.tp_degree > 1 and var is not None and \
                var.name in self.tp_sharded:
            size = -(-size // self.tp_degree)
        self.cache[name] = size
        return size


def _phase_of(op) -> str:
    role = op.attrs.get(OpRole.KEY, OpRole.Forward)
    try:
        role = int(role)
    except (TypeError, ValueError):
        return "forward"
    if role & OpRole.Backward:
        return "backward"
    if role & (OpRole.Optimize | OpRole.LRSched) or role == OpRole.Dist:
        return "optimize"
    return "forward"


def mp_sharded_vars(program: Program, tp_degree: int) -> Set[str]:
    """The vars a `tp_degree` tensor-parallel mesh holds at 1/tp per
    chip: everything the sharding-propagation analyzer proves
    mp-sharded (annotated weights, their grads' base vars, and the
    feature-sharded activations between a column- and row-parallel
    layer), plus their ``accum_of``-linked optimizer accumulators.
    Batch-independent — callers pricing many batch buckets of one
    program (the planner's `_RewritePoint`) compute it once and pass it
    to `analyze_program(tp_sharded=)`."""
    from .layout_analysis import propagate_shardings
    layout = propagate_shardings(program,
                                 mesh_shape={"mp": int(tp_degree)})
    out = {n for n, s in layout.specs.items() if "mp" in s.axes()}
    for b in program.blocks:
        for v in b.vars.values():
            owner = v.attrs.get("accum_of")
            if owner and owner in out:
                out.add(v.name)
    return out


def analyze_program(program: Program, batch: Optional[int] = None,
                    budget_bytes: Optional[int] = None,
                    dp_shard: Optional[int] = None,
                    zero_stage: Optional[int] = None,
                    tp_degree: Optional[int] = None,
                    tp_sharded: Optional[Set[str]] = None) -> Dict:
    """Full liveness report for `program`'s global block.

    Returns a dict with ``peak_bytes`` (persistables + peak live
    activations), ``persistable_bytes``, ``optimizer_slot_bytes``
    (accumulator / sharded-bucket persistables after sharding division),
    per-phase peaks (``phase_peaks``), the op index/type at the peak,
    the largest live vars at the peak (``top_live``), unknown-shape var
    count, and the ``fits``/``budget_bytes`` verdict.

    `batch` binds symbolic -1 dims; defaults to ``FLAGS_hbm_assume_batch``
    when set, else 1 (which makes batch-dynamic programs a lower bound —
    pass the real batch for a fits/OOM verdict that means anything).

    World-size-aware accounting (ZeRO stages 1-3,
    distributed/sharding.py): a persistable marked ``dp_shard`` (a
    sharded bucket — optimizer slots, stage-2 gradient accumulators, or
    a stage-3 param bucket — declared at the GLOBAL padded shape) is
    charged 1/degree per chip — the walker reports per-chip footprints.
    An APPLIED program therefore needs no stage argument: the stamps on
    its vars carry the whole story (stage-3 params additionally show up
    as gathered ACTIVATIONS with forward/backward-bounded liveness,
    which the live-set sweep prices for free).

    `dp_shard` (argument; defaults to ``FLAGS_hbm_dp_shard``)
    additionally PREDICTS sharding an unsharded program: per-param
    optimizer accumulators (``accum_of``-linked vars) are charged 1/N,
    answering "would ERNIE-large-b24 fit under ZeRO-1?" before the
    rewrite is ever applied.  `zero_stage` (defaults to
    ``FLAGS_hbm_zero_stage``) extends the prediction up the ladder:
    stage >= 3 also divides the parameters the pass would pack
    (`predicted_shardable_params`).  Stage-3 prediction is a LOWER
    bound — it does not model the transient gathered copies — so the
    applied program's walk is the authority (the planner prices applied
    clones, never predictions).

    `tp_degree` prices a TENSOR-PARALLEL mesh: the sharding-propagation
    analyzer (`static.propagate_shardings` over an {"mp": tp} mesh)
    decides which vars are mp-sharded — annotated weights, their
    optimizer accumulators (``accum_of``), and the feature-sharded
    activations between a column- and row-parallel layer — and each is
    charged 1/tp per chip.  Everything propagation can't prove sharded
    (replicated embeddings, partial sums, tainted vars) stays
    full-size, so the verdict is conservative.  `tp_sharded` takes the
    precomputed set (`mp_sharded_vars` — batch-independent) so repeated
    batch-bucket pricing skips the propagation re-run.
    """
    from ..core.flags import flag
    if batch is None:
        batch = int(flag("hbm_assume_batch", 0)) or 1
    if dp_shard is None:
        dp_shard = int(flag("hbm_dp_shard", 0)) or None
    if zero_stage is None:
        zero_stage = int(flag("hbm_zero_stage", 0)) or 1
    pred_shard = int(dp_shard) if dp_shard and int(dp_shard) > 1 else 0
    pred_stage = max(1, int(zero_stage)) if pred_shard else 0
    budget = hbm_budget_bytes() if budget_bytes is None else int(budget_bytes)
    block = program.global_block()
    tp = int(tp_degree) if tp_degree and int(tp_degree) > 1 else 0
    mp_sharded: Set[str] = set()
    if tp:
        # tp_sharded: the precomputed (batch-independent) set, so
        # callers pricing many batch buckets don't re-run propagation
        mp_sharded = (set(tp_sharded) if tp_sharded is not None
                      else mp_sharded_vars(program, tp))
    sizer = _Sizer(block, batch, mp_sharded, tp)

    var_desc = {}
    persistable: Set[str] = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.persistable:
                persistable.add(v.name)
                var_desc.setdefault(v.name, v)
    # prediction mode only divides state the sharding pass would ACTUALLY
    # partition — an Adamax moment or a MasterParam-carrying op's slots
    # stay replicated, so the verdict never claims memory the rewrite
    # cannot deliver
    shardable: Set[str] = set()
    shardable_params: Set[str] = set()
    if pred_shard:
        from ..distributed.sharding import predicted_shardable_slots
        shardable = predicted_shardable_slots(program)
        if pred_stage >= 3:
            from ..distributed.sharding import predicted_shardable_params
            shardable_params = predicted_shardable_params(program)
    persistable_bytes = 0
    slot_bytes = 0
    param_bytes = 0
    for n in sorted(persistable):
        raw = sizer(n)
        v = var_desc.get(n)
        marked = int((v.attrs.get("dp_shard") or 0) if v is not None else 0)
        is_slot = v is not None and bool(
            (marked and not v.attrs.get("zero_param_bucket"))
            or v.attrs.get("accum_of"))
        is_param = v is not None and bool(
            v.is_parameter or v.attrs.get("zero_param_bucket"))
        if marked > 1:
            cost = -(-raw // marked)          # per-chip slice of the bucket
        elif pred_shard and n in shardable:
            cost = -(-raw // pred_shard)      # predicted ZeRO slot share
        elif pred_shard and n in shardable_params:
            cost = -(-raw // pred_shard)      # predicted ZeRO-3 param share
        else:
            cost = raw
        persistable_bytes += cost
        if is_slot:
            slot_bytes += cost
        if is_param:
            param_bytes += cost

    ops = [op for op in block.ops if op.type not in ("feed", "fetch")]

    # Pass 1: rep propagation + last-use.  A fusable/alias op's output is
    # a view of its ROOT buffer(s); a use of the view is a use of every
    # root.  Defs precede uses in block order, so one pass suffices.
    reps: Dict[str, frozenset] = {}
    last_use: Dict[str, int] = {}
    for i, op in enumerate(ops):
        for slot, names in op.inputs.items():
            released = _use_released(op.type, slot)
            for n in names:
                if not n:
                    continue
                if not released:
                    last_use[n] = i
                    for r in reps.get(n, ()):
                        last_use[r] = i
        if op.type == "optimization_barrier":
            # positional aliasing: Out[i] IS X[i] (jax.lax.
            # optimization_barrier returns its operand tuple unchanged).
            # The union rule below would merge every operand pair into
            # one root set — a multi-operand barrier (the ZeRO-3 gather
            # prefetch pins bucket k+1's gather to bucket k's reads)
            # would then chain ALL gathered buckets into a single
            # lifetime and the walker would charge the whole parameter
            # set as simultaneously live.
            xs = op.inputs.get("X", [])
            outs = op.outputs.get("Out", [])
            for xn, on in zip(xs, outs):
                if not on:
                    continue
                reps[on] = (reps.get(xn) or frozenset((xn,))) \
                    if xn and xn not in persistable else frozenset()
        elif op.type in _ALIAS_OPS or op.type in _FUSABLE_OPS:
            roots = frozenset(
                r
                for n in op.input_names() if n and n not in persistable
                for r in (reps.get(n) or frozenset((n,))))
            for n in op.output_names():
                if n:
                    reps[n] = roots

    # Pass 2: live-set sweep.  Outputs of alias/fusable ops cost 0 (rep
    # accounting keeps their roots alive); other outputs may REUSE the
    # buffer of a same-size input dying at this very op (XLA buffer
    # assignment's in-place reuse — softmax writing over its logits, a
    # grad writing over the activation it consumes).
    cost_of: Dict[str, int] = {}
    live: Set[str] = set()
    cur = 0
    for v in block.vars.values():
        if v.is_data and not v.persistable:
            c = sizer(v.name)
            cost_of[v.name] = c
            live.add(v.name)
            cur += c

    peak = cur
    peak_idx, peak_type = -1, "feed"
    peak_live: Set[str] = set(live)
    phase_peaks = {"forward": cur, "backward": 0, "optimize": 0}

    for i, op in enumerate(ops):
        free_output = op.type in _ALIAS_OPS or op.type in _FUSABLE_OPS
        dying = [n for n in set(op.input_names())
                 if n in live and last_use.get(n, -1) <= i
                 and cost_of.get(n, 0) > 0]
        internal = _op_internal_bytes(op, sizer)
        for n in op.output_names():
            if not n or n in persistable or n in live:
                continue
            c = (0 if free_output else sizer(n)) + internal
            internal = 0  # charge kernel-internal scratch once
            if c > 0:
                for j, d in enumerate(dying):
                    if cost_of[d] == c:
                        # take over the dying input's buffer
                        cost_of[d] = 0
                        dying.pop(j)
                        break
                else:
                    cur += c
                    cost_of[n] = c
                    live.add(n)
                    continue
            cost_of[n] = c
            live.add(n)
        phase = _phase_of(op)
        if cur > phase_peaks[phase]:
            phase_peaks[phase] = cur
        if cur > peak:
            peak, peak_idx, peak_type = cur, i, op.type
            peak_live = set(live)
        # inputs AND outputs whose last use is behind us die here — and
        # so do the ROOT buffers of any alias among them: a buffer that
        # is only ever read through alias views (ZeRO-3's slice → seg →
        # reshape-to-param gather chains) never reappears by name in a
        # later op, so sweeping only the op's own names would leak it
        # forever.  (Backward ops formally mention every forward input,
        # which is why ordinary residual roots never hit this path.)
        mentioned = set(op.input_names()) | set(op.output_names())
        for n in list(mentioned):
            mentioned |= reps.get(n, frozenset())
        for n in mentioned:
            if n in live and last_use.get(n, -1) <= i:
                cur -= cost_of.get(n, 0)
                live.discard(n)

    top_live = sorted(((cost_of.get(n, 0), n) for n in peak_live),
                      reverse=True)[:12]
    return {
        "batch": int(batch),
        "dp_shard": int(pred_shard) if pred_shard else None,
        "tp_degree": tp or None,
        "persistable_bytes": int(persistable_bytes),
        "optimizer_slot_bytes": int(slot_bytes),
        # per-chip PARAMETER state (replicated params, or the 1/degree
        # slice of ZeRO-3 dp_shard param buckets) — the stage-3 claim
        # the shard smoke and docs tables report
        "parameter_bytes": int(param_bytes),
        "activation_peak_bytes": int(peak),
        "peak_bytes": int(persistable_bytes + peak),
        "phase_peaks": {k: int(v + persistable_bytes)
                        for k, v in phase_peaks.items()},
        "peak_op_index": peak_idx,
        "peak_op_type": peak_type,
        "top_live": [(n, int(c)) for c, n in top_live],
        "n_ops": len(ops),
        "n_unknown_vars": len(set(sizer.unknown)),
        "budget_bytes": int(budget),
        # fits grants the calibrated XLA-remat slack (see XLA_REMAT_SLACK)
        "fits_budget_bytes": int(budget * XLA_REMAT_SLACK),
        "fits": bool(persistable_bytes + peak <= budget * XLA_REMAT_SLACK),
    }


def estimate_peak_bytes(program: Program, batch: Optional[int] = None) -> int:
    """Predicted peak HBM bytes of one training step of `program`
    (persistable state + peak live activations; see `analyze_program`
    for the full report).  Runs entirely at build time — no device."""
    return analyze_program(program, batch=batch)["peak_bytes"]


# ---------------------------------------------------------------------------
# checkpoint selection (auto-remat)
# ---------------------------------------------------------------------------
def _is_score_softmax(block, op) -> bool:
    """A softmax over an attention score tensor (rank >= 3): the one
    softmax per transformer layer that is not the loss head."""
    if op.type != "softmax":
        return False
    names = op.inputs.get("X", [])
    if not names or not block.has_var(names[0]):
        return False
    shape = block.var(names[0]).shape
    return shape is not None and len(shape) >= 3


def select_layer_checkpoints(program: Program) -> List[str]:
    """Checkpoint vars at transformer LAYER boundaries — the same
    boundaries a user hands `RecomputeOptimizer` (`recompute_configs
    {"checkpoints": [...]}`).

    For each attention core in the forward ops (softmax over a rank>=3
    score tensor, flash_attention, ring_attention, multihead_matmul) the
    nearest PRECEDING layer_norm output is selected — one checkpoint per
    layer, at the layer's entry, so backward replays one layer at a time
    from O(L) boundary activations instead of retaining every
    intermediate.  Falls back to every layer_norm output when the
    program has norms but no recognizable attention (conv stacks etc.
    return [] — no remat)."""
    block = program.global_block()
    fwd_ops = [op for op in block.ops
               if _phase_of(op) == "forward" and op.type != "feed"]
    ln_outs: List[str] = []   # layer_norm outputs in program order
    picks: List[str] = []
    seen: Set[str] = set()
    for op in fwd_ops:
        if op.type == "layer_norm":
            outs = op.outputs.get("Y") or op.outputs.get("Out") or []
            if outs and outs[0]:
                ln_outs.append(outs[0])
        elif op.type in _ATTENTION_CORE_OPS or _is_score_softmax(block, op):
            if ln_outs and ln_outs[-1] not in seen:
                picks.append(ln_outs[-1])
                seen.add(ln_outs[-1])
    if picks:
        return picks
    return list(dict.fromkeys(ln_outs))

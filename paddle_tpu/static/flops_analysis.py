"""Compile-time per-op FLOPs accounting: the exact denominator for MFU
and the auto-parallel planner's third cost substrate.

Until now the framework could not OBSERVE its own north-star metric:
`bench.py` guessed FLOPs with the analytic ``6*params + 12*L*s*h``
formula, and the planner had per-op HBM (`static/memory_analysis.py`)
and per-op wire bytes (`static.collective_wire_bytes`) but no per-op
compute cost.  This module walks the program IR — the same op list the
executor jits — and prices every op from its resolved shapes:

  * `analyze_flops(program, batch=...)` — per-op table + per-class and
    per-phase (forward / backward / optimize) totals.  Shape resolution
    is the memory walker's machinery (`memory_analysis._Sizer`):
    symbolic -1 batch dims bind to `batch`, derived names
    (``@GRAD``/``@RC``/...) borrow the base var's shape.
  * `peak_flops_per_chip()` — the MFU denominator's denominator: chip
    peak from ``PADDLE_TPU_PEAK_FLOPS`` (env), defaulting to the v5e
    bf16 peak on TPU and 0 (=unknown, MFU unreported) elsewhere.

Accounting conventions (chosen to agree with the analytic estimate the
whole perf record is denominated in — bench cross-checks the two and
warns on >10% drift):

  * matmul-class ops (``mul``/``matmul``/``matmul_v2``/conv) cost
    2·M·K·N multiply-accumulate FLOPs from their resolved operand
    shapes; a ``*_grad`` op costs 2× its forward op (dX and dY are each
    one forward-sized matmul).
  * attention cores (``flash_attention``/``ring_attention``/
    ``multihead_matmul`` and the materialized matmul+softmax path) cost
    the QKᵀ + PV matmuls: 4·B·S²·H forward per layer.  Flash backward
    recomputes blocks on the fly (~2.5× fwd on the chip); the walker
    still charges 2× — MODEL flops, the MFU convention — so a flash run
    reports the same MFU arithmetic as the XLA path.
  * embeddings (``lookup_table[_v2]``) are charged their DENSE
    one-hot-matmul equivalent (2·tokens·V·H fwd, 2× bwd), matching the
    ``6·params`` convention the baseline record uses.  The per-class
    breakdown keeps them separable (``by_class["embedding"]``) for a
    consumer that wants gather-true chip flops instead.
  * elementwise/normalization/loss ops carry a small per-element cost
    table; optimizer ops a per-param-element cost; collectives cost 0
    FLOPs here (their cost is wire bytes — `collective_wire_bytes`).

The per-op table is the planner substrate: every candidate program
rewrite (remat replays, ZeRO buckets, elastic folds) shows up as op-list
changes, so re-walking the rewritten program prices the candidate.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..core.program import Program

__all__ = ["analyze_flops", "estimate_step_flops", "peak_flops_per_chip",
           "INT8_MXU_RATE",
           "PEAK_FLOPS_ENV", "DEFAULT_TPU_PEAK_FLOPS"]

PEAK_FLOPS_ENV = "PADDLE_TPU_PEAK_FLOPS"

# v5e bf16 MXU peak — the chip the north star is denominated in
DEFAULT_TPU_PEAK_FLOPS = 197e12

# int8 MXU rate multiplier over the bf16 peak: the v5e runs int8
# matmuls at 394 vs 197 TOPS (tools/bench_int8.py validates the 2x
# through preferred_element_type=int32) — the calibrated roofline
# divides int8_flops by INT8_MXU_RATE*peak instead of peak
INT8_MXU_RATE = 2.0


def peak_flops_per_chip(platform: Optional[str] = None) -> float:
    """Chip peak FLOPs/s the MFU gauge divides by.  Env override
    ``PADDLE_TPU_PEAK_FLOPS`` wins; else v5e bf16 peak on TPU and 0
    (= unknown; MFU is not reported) on CPU hosts.  `platform` skips
    device discovery when the caller already knows it."""
    raw = os.environ.get(PEAK_FLOPS_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    if platform is None:
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception:
            platform = "cpu"
    return DEFAULT_TPU_PEAK_FLOPS if platform == "tpu" else 0.0


# ---------------------------------------------------------------------------
# per-class cost tables
# ---------------------------------------------------------------------------
_MATMUL_OPS = frozenset(("mul", "matmul", "matmul_v2", "bmm",
                         "int8_matmul"))

_ATTENTION_OPS = frozenset(("flash_attention", "ring_attention",
                            "multihead_matmul"))

_EMBEDDING_OPS = frozenset(("lookup_table", "lookup_table_v2"))

_CONV_OPS = frozenset(("conv2d", "depthwise_conv2d", "conv2d_transpose",
                       "conv3d"))

# optimizer update cost per PARAM element (reads+muls+adds of the update
# rule; master-weight AMP variants ride the same table)
_OPTIMIZER_FLOPS_PER_ELEM = {
    "sgd": 2, "momentum": 4, "lars_momentum": 6, "dgc_momentum": 6,
    "adam": 12, "adamw": 14, "lamb": 16, "adamax": 10, "adagrad": 6,
    "decayed_adagrad": 8, "adadelta": 8, "rmsprop": 8, "ftrl": 8,
    "dpsgd": 6,
}

# forward cost per OUTPUT element for the cheap (near-)elementwise tier;
# anything recognizably elementwise but unlisted costs the default 1
_ELEMENTWISE_FLOPS_PER_ELEM = {
    "softmax": 5, "log_softmax": 6, "softmax_with_cross_entropy": 7,
    "sigmoid_cross_entropy_with_logits": 6, "cross_entropy": 4,
    "layer_norm": 8, "batch_norm": 8, "sync_batch_norm": 8,
    "gelu": 10, "tanh": 4, "sigmoid": 4, "exp": 4, "log": 4,
    "sqrt": 2, "rsqrt": 2, "square": 1, "relu": 1, "relu6": 2,
    "dropout": 2, "mean": 1, "sum": 1, "scale": 1, "clip": 2,
    "pow": 4, "elementwise_pow": 4,
}

# zero-cost layout/bookkeeping ops: charging their numel would double-
# count buffers the memory walker already treats as aliases
_FREE_OPS = frozenset((
    "reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
    "unsqueeze2", "flatten", "flatten2", "flatten_contiguous_range",
    "transpose", "transpose2", "assign", "share_data", "shape",
    "optimization_barrier", "fill_constant", "fill_any_like",
    "fill_zeros_like", "feed", "fetch", "increment", "seed", "print",
    "py_func",
))


def _collective_ops() -> frozenset:
    from .verifier import _COLLECTIVE_OPS
    return _COLLECTIVE_OPS


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return int(n)


class _Shaper:
    """name -> concrete shape tuple via the memory walker's resolver
    (-1 dims bind to batch; @GRAD/@RC/... borrow the base var)."""

    def __init__(self, block, batch: int):
        from .memory_analysis import _Sizer
        self._sizer = _Sizer(block, batch)
        self.batch = self._sizer.batch
        self.unknown: List[str] = []

    def __call__(self, name: Optional[str]) -> Optional[Tuple[int, ...]]:
        if not name:
            return None
        var = self._sizer.var_of(name)
        shape = var.shape if var is not None else None
        if shape is None:
            self.unknown.append(name)
            return None
        return tuple(self.batch if d in (-1, None) else int(d)
                     for d in shape)


def _first(op, slot):
    names = op.inputs.get(slot, [])
    return names[0] if names else None


def _first_out(op, slot):
    names = op.outputs.get(slot, [])
    return names[0] if names else None


def _matmul_flops(op, shaper, base: str) -> int:
    if base == "mul":
        sx = shaper(_first(op, "X"))
        sy = shaper(_first(op, "Y"))
        if sx is None or sy is None:
            return 0
        a = int(op.attrs.get("x_num_col_dims", 1))
        b = int(op.attrs.get("y_num_col_dims", 1))
        m = _prod(sx[:a])
        k = _prod(sx[a:])
        n = _prod(sy[b:])
        return 2 * m * k * n
    if base == "int8_matmul":
        # weight-only int8: X [..., K] contracts its last dim against
        # the int8 W [K, N] slot (there is no Y)
        sx = shaper(_first(op, "X"))
        sw = shaper(_first(op, "W"))
        if sx is None or sw is None or len(sw) < 2 or not sx:
            return 0
        return 2 * _prod(sx[:-1]) * sx[-1] * sw[-1]
    # matmul / matmul_v2 / bmm: batched [..., m, k] x [..., k, n]
    sx = shaper(_first(op, "X"))
    sy = shaper(_first(op, "Y"))
    if sx is None or sy is None or len(sx) < 2 or len(sy) < 2:
        return 0
    tx = bool(op.attrs.get("transpose_X", op.attrs.get("trans_x", False)))
    ty = bool(op.attrs.get("transpose_Y", op.attrs.get("trans_y", False)))
    m, k = (sx[-1], sx[-2]) if tx else (sx[-2], sx[-1])
    n = sy[-2] if ty else sy[-1]
    batch = max(_prod(sx[:-2]), _prod(sy[:-2]))
    return 2 * batch * m * k * n


def _attention_flops(op, shaper, base: str) -> int:
    sq = shaper(_first(op, "Q")) if base != "multihead_matmul" \
        else shaper(_first(op, "Input"))
    if sq is None:
        return 0
    if base == "flash_attention":
        # Q [B, H, S, D]: QK^T + PV, 2*(B*H*S*S*D) MACs each
        if len(sq) < 4:
            return 0
        b, h, s, d = sq[-4], sq[-3], sq[-2], sq[-1]
        return 4 * b * h * s * s * d
    if base == "ring_attention":
        # Q [B, S, H*D]: head split preserves total MACs
        if len(sq) < 3:
            return 0
        b, s, hd = sq[-3], sq[-2], sq[-1]
        return 4 * b * s * s * hd
    # multihead_matmul: fused QKV projections + attention core over
    # Input [B, S, H] with weights [H, H]
    if len(sq) < 3:
        return 0
    b, s, h = sq[-3], sq[-2], sq[-1]
    return 3 * 2 * b * s * h * h + 4 * b * s * s * h


def _embedding_flops(op, shaper) -> int:
    """Dense one-hot-matmul equivalent (see module docstring): tokens ×
    table, 2 FLOPs per MAC."""
    sw = shaper(_first(op, "W"))
    ids = shaper(_first(op, "Ids"))
    if sw is None or len(sw) < 2:
        # grad ops keep the W slot; fall back to the minted W@GRAD
        sw = shaper(_first_out(op, "W@GRAD"))
    if sw is None or ids is None or len(sw) < 2:
        return 0
    return 2 * _prod(ids) * _prod(sw[-2:])


def _conv_flops(op, shaper) -> int:
    sf = shaper(_first(op, "Filter"))
    so = shaper(_first_out(op, "Output") or _first_out(op, "Out"))
    if so is None:
        so = shaper(_first(op, "Input"))
    if sf is None or so is None or not sf:
        return 0
    macs_per_out = _prod(sf) // max(1, int(sf[0]))
    return 2 * _prod(so) * macs_per_out


def _optimizer_flops(op, shaper) -> int:
    per = _OPTIMIZER_FLOPS_PER_ELEM[op.type]
    sp = shaper(_first(op, "Param") or _first(op, "param"))
    if sp is None:
        return 0
    return per * _prod(sp)


def _elementwise_flops(op, shaper, base_type: str) -> int:
    per = _ELEMENTWISE_FLOPS_PER_ELEM.get(base_type, 1)
    best = 0
    for slot, names in op.outputs.items():
        for n in names:
            s = shaper(n)
            if s is not None:
                best = max(best, _prod(s))
    if best == 0:
        for slot, names in op.inputs.items():
            for n in names:
                s = shaper(n)
                if s is not None:
                    best = max(best, _prod(s))
    if base_type == "sum":
        # n-way elementwise accumulate: (n-1) adds per element
        k = max(1, sum(len(v) for v in op.inputs.values()) - 1)
        return k * best
    return per * best


def _classify(op_type: str) -> Tuple[str, str]:
    """(class, base forward type) — a ``*_grad`` op inherits its forward
    op's class and is priced at 2× the forward cost."""
    base = op_type[:-len("_grad")] if op_type.endswith("_grad") else op_type
    if base in _MATMUL_OPS:
        return "matmul", base
    if base in _ATTENTION_OPS:
        return "attention", base
    if base in _EMBEDDING_OPS:
        return "embedding", base
    if base in _CONV_OPS:
        return "conv", base
    if base in _OPTIMIZER_FLOPS_PER_ELEM:
        return "optimizer", base
    if base in _collective_ops():
        return "collective", base
    if base in _FREE_OPS:
        return "free", base
    return "elementwise", base


def _op_flops(op, shaper) -> Tuple[int, str]:
    cls, base = _classify(op.type)
    grad = op.type.endswith("_grad")
    if cls == "free" or cls == "collective":
        return 0, cls
    if cls == "matmul":
        f = _matmul_flops(op, shaper, base)
    elif cls == "attention":
        f = _attention_flops(op, shaper, base)
    elif cls == "embedding":
        f = _embedding_flops(op, shaper)
    elif cls == "conv":
        f = _conv_flops(op, shaper)
    elif cls == "optimizer":
        f = _optimizer_flops(op, shaper)
    else:
        f = _elementwise_flops(op, shaper, base)
    if grad:
        f *= 2
    return int(f), cls


def analyze_flops(program: Program, batch: Optional[int] = None) -> Dict:
    """Per-op FLOPs report for `program`'s global block.

    Returns a dict with ``total_flops`` (one training step, all phases),
    ``phase_flops`` (forward / backward / optimize — fwd+bwd are the MFU
    numerator; the optimize slice is per-step, not per-token),
    ``by_class`` (matmul / attention / embedding / conv / elementwise /
    optimizer), the full ``per_op`` table (block, index, type, class,
    phase, flops — the planner substrate), ``matmul_fraction`` (how
    MXU-bound the step is), and bookkeeping (``batch``, ``n_ops``,
    ``n_unknown_vars``).

    `batch` binds symbolic -1 dims; defaults to ``FLAGS_hbm_assume_batch``
    when set, else 1 — pass the real batch for totals that mean anything
    (FLOPs scale linearly in it, unlike the HBM walk).
    """
    from ..core.flags import flag
    from .memory_analysis import _phase_of
    if batch is None:
        batch = int(flag("hbm_assume_batch", 0)) or 1
    block = program.global_block()
    shaper = _Shaper(block, batch)

    per_op: List[Dict] = []
    by_class: Dict[str, int] = {}
    phase_flops = {"forward": 0, "backward": 0, "optimize": 0}
    total = 0
    int8 = 0
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        f, cls = _op_flops(op, shaper)
        phase = _phase_of(op)
        per_op.append({"block": block.idx, "index": i, "type": op.type,
                       "class": cls, "phase": phase, "flops": int(f)})
        if f:
            by_class[cls] = by_class.get(cls, 0) + f
            phase_flops[phase] += f
            total += f
            if op.type == "int8_matmul":
                int8 += f
    matmul_like = (by_class.get("matmul", 0) + by_class.get("attention", 0)
                   + by_class.get("conv", 0))
    return {
        "batch": int(shaper.batch),
        "total_flops": int(total),
        # the slice running at the int8 MXU rate (INT8_MXU_RATE x peak);
        # roofline compute time = (total - int8)/peak + int8/(rate*peak)
        "int8_flops": int(int8),
        "phase_flops": {k: int(v) for k, v in phase_flops.items()},
        "by_class": {k: int(v) for k, v in sorted(by_class.items())},
        "per_op": per_op,
        "matmul_fraction": (matmul_like / total) if total else 0.0,
        "n_ops": len(per_op),
        "n_unknown_vars": len(set(shaper.unknown)),
    }


def estimate_step_flops(program: Program,
                        batch: Optional[int] = None) -> int:
    """Total FLOPs of one training step of `program` (forward + backward
    + optimizer; see `analyze_flops` for the breakdown)."""
    return analyze_flops(program, batch=batch)["total_flops"]

"""paddle.static.nn — the 2.0 static layer namespace (reference
python/paddle/static/nn/__init__.py): re-exports of the fluid layer
functions that stay static-graph-only in 2.0."""
from .layers import (  # noqa: F401
    fc, batch_norm, embedding, bilinear_tensor_product, conv2d,
    conv2d_transpose, conv3d, conv3d_transpose, crf_decoding, data_norm,
    deformable_conv, group_norm, hsigmoid, instance_norm, layer_norm,
    multi_box_head, nce, prelu, row_conv, spectral_norm,
)
from .control_flow import case, switch_case, cond  # noqa: F401
from ..tensor.compat import create_parameter  # noqa: F401

__all__ = ["fc", "batch_norm", "embedding", "bilinear_tensor_product",
           "case", "conv2d", "conv2d_transpose", "conv3d",
           "conv3d_transpose", "create_parameter", "crf_decoding",
           "data_norm", "deformable_conv", "group_norm", "hsigmoid",
           "instance_norm", "layer_norm", "multi_box_head", "nce",
           "prelu", "row_conv", "spectral_norm", "switch_case", "cond"]

"""LayerHelper: the bridge between layer functions and the Program IR.

Analog of /root/reference/python/paddle/fluid/layer_helper.py — every layer
function makes one of these to create parameters (registering their
initializer ops in the startup program), temp output vars, and append ops to
the current main program block.
"""
from __future__ import annotations

from ..core.program import (default_main_program, default_startup_program,
                            unique_name, VarDesc)
from .initializer import Xavier, Constant, Initializer
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # -- parameters ---------------------------------------------------------
    def create_parameter(self, attr, shape, dtype="float32",
                         is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            default_initializer = Constant(0.0) if is_bias else Xavier()
        init = attr.initializer or default_initializer
        name = attr.name or unique_name(f"{self.name}.w" if not is_bias
                                        else f"{self.name}.b")
        # parameter lives in the main program's global block
        p = self.main_program.global_block().create_parameter(
            name, shape, dtype, initializer=None, trainable=attr.trainable)
        p.attrs["learning_rate"] = attr.learning_rate
        p.attrs["regularizer"] = attr.regularizer
        p.attrs["need_clip"] = attr.need_clip
        # init op goes to the startup program
        init(p, self.startup_program.global_block())
        return p

    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False):
        return self.block.create_var(
            name=unique_name(f"{self.name}.tmp"), dtype=dtype,
            stop_gradient=stop_gradient)

    def create_global_variable(self, shape, dtype="float32", persistable=False,
                               name=None, initializer=None):
        name = name or unique_name(f"{self.name}.global")
        v = self.main_program.global_block().create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable)
        if initializer is not None:
            initializer(v, self.startup_program.global_block())
        return v

    # -- ops ----------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.block.append_op(type, inputs, outputs, attrs)

    def append_activation(self, out_var, act):
        if act is None:
            return out_var
        tmp = self.create_variable_for_type_inference(out_var.dtype)
        self.append_op(act, inputs={"X": out_var}, outputs={"Out": tmp})
        return tmp

    def input(self, name):
        v = self.kwargs.get(name)
        if isinstance(v, str):
            return self.block.var(v)
        return v

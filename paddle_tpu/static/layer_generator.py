"""Auto-generated layer functions from the op registry.

Analog of /root/reference/python/paddle/fluid/layers/
layer_function_generator.py — the reference autogenerates ~half its layer
surface from each op's OpProto; here the registry's slot declarations
(ops/registry.py Slot) play the OpProto role.  Only mechanically-shaped ops
(var inputs + a single `Out`) are generated; anything needing parameter
creation or multi-output plumbing gets a hand-written layer in layers.py.

Generated signature: positional args bind the op's declared input slots in
order; keyword args become op attrs; `name=` picks the output var name
prefix.
"""
from __future__ import annotations

from typing import List

from ..ops.registry import all_ops, get_op_info
from .layer_helper import LayerHelper

__all__ = ["generate_layer_fns"]

# ops that are internal machinery or already exposed through a dedicated
# API surface (collectives → paddle.distributed, IO ops → executor/io)
_SKIP_PREFIXES = (
    "c_", "p_", "fake_", "fused_", "fusion_", "pull_", "push_", "partial_",
    "create_", "save", "load", "send", "recv", "listen", "fetch", "feed",
    "read", "write_to_array", "read_from_array", "enqueue", "dequeue",
    "queue", "gen_", "checkpoint", "distributed_", "lookup_sparse",
    "merge_", "split_ids", "ref_by", "moving_average_abs",
)
_SKIP_EXACT = {
    "allreduce", "alltoall", "broadcast", "barrier", "cast_with_ptr",
    "print", "assert", "delete_var", "run_program", "while",
    "conditional_block", "select_input", "select_output",
    # autodiff/collective internals — not user layers
    "grad_add", "scale_by_world_size", "share_data",
}

# fallback output dtype when shape inference bails (infer_shape_for_op
# normally overwrites the declared dtype from abstract kernel evaluation,
# but returns early on unknown input shapes — the static dtype must still
# be right for AMP cast insertion and recv shape/dtype attrs)
_OUT_DTYPE = {
    "arg_max": "int64", "arg_min": "int64",
    "equal_all": "bool", "isfinite": "bool", "isfinite_v2": "bool",
    "isinf_v2": "bool", "isnan_v2": "bool", "is_empty": "bool",
    "allclose": "bool", "shape": "int32", "size": "int64",
    "multinomial": "int64", "where_index": "int64", "sampling_id": "int64",
    "histogram": "int64", "lod_array_length": "int64",
    # int input, float output — the first-input-dtype fallback is wrong
    "one_hot_v2": "float32",
}


def _make_layer_fn(op_type: str):
    info = get_op_info(op_type)
    slot_names = [s.name for s in info.inputs]

    def fn(*args, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        if len(args) > len(slot_names):
            raise TypeError(
                f"{op_type} takes at most {len(slot_names)} tensor args "
                f"({slot_names}), got {len(args)}")
        inputs = {}
        first = None
        for slot, arg in zip(info.inputs, args):
            if arg is None:
                continue
            vs = list(arg) if isinstance(arg, (list, tuple)) else [arg]
            if first is None and vs:
                first = vs[0]
            inputs[slot.name] = vs
        dtype = _OUT_DTYPE.get(op_type) or (
            first.dtype if first is not None else "float32")
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op(op_type, inputs=inputs, outputs={"Out": [out]},
                         attrs=attrs)
        return out

    fn.__name__ = op_type
    fn.__qualname__ = op_type
    fn.__doc__ = (
        f"Layer for op `{op_type}` (auto-generated from the op registry; "
        f"layer_function_generator.py analog).  Positional args: "
        f"{slot_names}; keyword args become op attrs.")
    return fn


def generate_layer_fns(namespace: dict, existing) -> List[str]:
    """Install generated layer functions for every mechanically-shaped op
    not already covered; returns the generated names."""
    made = []
    existing = set(existing)
    for op_type in all_ops():
        if op_type.endswith("_grad") or op_type in existing:
            continue
        if op_type.startswith(_SKIP_PREFIXES) or op_type in _SKIP_EXACT:
            continue
        info = get_op_info(op_type)
        # exactly one plain `Out` (duplicable Out* / optional Out? ops need
        # hand-written plumbing — e.g. static_rnn's sub_block attrs)
        if len(info.outputs) != 1 or not info.inputs:
            continue
        out = info.outputs[0]
        if out.name != "Out" or out.duplicable or out.optional:
            continue
        namespace[op_type] = _make_layer_fn(op_type)
        made.append(op_type)
    return made

"""Composite nets (analog of /root/reference/python/paddle/fluid/nets.py:
simple_img_conv_pool :28, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""
from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "glu", "scaled_dot_product_attention", "attention_core"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(input, num_filters, filter_size,
                             stride=conv_stride, padding=conv_padding,
                             dilation=conv_dilation, groups=conv_groups,
                             param_attr=param_attr, bias_attr=bias_attr,
                             act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]
    n = len(conv_num_filter)

    def _expand(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    padding = _expand(conv_padding)
    fsize = _expand(conv_filter_size)
    with_bn = _expand(conv_with_batchnorm)
    drop = _expand(conv_batchnorm_drop_rate)
    pattr = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * n
    for i in range(n):
        local_act = conv_act if not with_bn[i] else None
        tmp = layers.conv2d(tmp, conv_num_filter[i], fsize[i],
                            padding=padding[i], param_attr=pattr[i],
                            act=local_act)
        if with_bn[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            if drop[i] > 0:
                tmp = layers.dropout(tmp, dropout_prob=drop[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    conv_out = layers.sequence_conv(input, num_filters, filter_size,
                                    param_attr=param_attr,
                                    bias_attr=bias_attr, act=act)
    return layers.sequence_pool(conv_out, pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def attention_core(q, k, v, d_key, dropout_rate=0.0, merge_shape=None):
    """Attention over already-head-split [b, h, t, d] tensors; dispatches
    to the Pallas flash op when enabled.  Returns merged [b, t, h*d]
    (`merge_shape` overrides the build-time (t, h*d) when the runtime
    tensors are shards — tensor_parallel.parallel_attention)."""
    from ..ops.attention import use_flash_for
    seq = q.shape[2] if q.shape is not None and len(q.shape) > 2 else None
    seq = seq if isinstance(seq, int) and seq > 0 else None
    if use_flash_for(seq) and not dropout_rate:
        # emit the Pallas flash op instead of the score-matrix graph
        helper = layers.LayerHelper("flash_attention")
        ctx = helper.create_variable_for_type_inference(q.dtype)
        ctx.shape = tuple(q.shape)
        helper.append_op("flash_attention",
                         inputs={"Q": [q], "K": [k], "V": [v]},
                         outputs={"Out": [ctx]}, attrs={"causal": False})
    else:
        scaled = layers.scale(q, scale=d_key ** -0.5)
        logits = layers.matmul(scaled, k, transpose_y=True)
        weights = layers.softmax(logits)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate)
        ctx = layers.matmul(weights, v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])  # [b, t, h, d]
    if merge_shape is None:
        t, h, d = ctx.shape[1], ctx.shape[2], ctx.shape[3]
        merge_shape = (t, h * d)
    out = layers.reshape(ctx, [-1, merge_shape[0], merge_shape[1]])
    out.shape = (-1,) + tuple(merge_shape)
    return out


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0, sequence_parallel=False,
                                 causal=False):
    """Multi-head attention built from primitive ops (nets.py:503).  The
    flash/ring Pallas kernel lives in paddle_tpu.ops.attention; this is
    the graph-API form.

    ``sequence_parallel=True`` emits the `ring_attention` op on the
    merged-head [b, t, h*d] tensors instead of the score-matrix graph:
    under a CompiledProgram whose BuildStrategy sets
    ``sequence_parallel_degree`` the sequence dim is sharded over the
    "sp" mesh axis and K/V rotate around the ring (O(S/n) activations,
    no S² scores); outside any mesh the op degrades to plain attention,
    so the same program runs single-chip for debugging."""
    if sequence_parallel:
        if dropout_rate:
            raise ValueError(
                "scaled_dot_product_attention(sequence_parallel=True) "
                "does not support attention-probability dropout — the "
                "probs are never materialized")
        from ..ops.attention import SP_RING_ID
        helper = layers.LayerHelper("ring_attention")
        out = helper.create_variable_for_type_inference(queries.dtype)
        out.shape = tuple(queries.shape) if queries.shape else None
        helper.append_op("ring_attention",
                         inputs={"Q": [queries], "K": [keys],
                                 "V": [values]},
                         outputs={"Out": [out]},
                         attrs={"causal": bool(causal),
                                "ring_id": SP_RING_ID,
                                "num_heads": int(num_heads)})
        return out
    if causal:
        raise NotImplementedError(
            "causal masking is only wired for the sequence_parallel "
            "(ring_attention) path; the score-matrix graph here is the "
            "bidirectional BERT/ERNIE form")
    d_key = queries.shape[-1] // num_heads

    def _split_heads(x):
        if num_heads == 1:
            return x
        t, c = x.shape[1], x.shape[2]
        x = layers.reshape(x, [-1, t, num_heads, c // num_heads])
        return layers.transpose(x, [0, 2, 1, 3])  # [b, h, t, d]

    q, k, v = _split_heads(queries), _split_heads(keys), _split_heads(values)
    if num_heads == 1:
        scaled = layers.scale(q, scale=d_key ** -0.5)
        logits = layers.matmul(scaled, k, transpose_y=True)
        weights = layers.softmax(logits)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate)
        return layers.matmul(weights, v)
    return attention_core(q, k, v, d_key, dropout_rate)

"""Optimizers: program-rewriting minimize() — backward + optimizer ops.

Analog of /root/reference/python/paddle/fluid/optimizer.py (Optimizer.minimize
:908 = backward :736 + apply_gradients :802; _create_optimization_pass :624
appends one optimizer op per parameter).  SGD/Momentum/Adam/... map onto the
optimizer kernels in paddle_tpu.ops.kernels.optimizers; accumulators
(moments, beta pows) are persistable vars initialised in the startup program,
so optimizer state lives in the same Scope as parameters and checkpoints the
same way (P19).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.program import (Program, VarDesc, OpRole, default_main_program,
                            default_startup_program, unique_name)
from .backward import append_backward
from .layer_helper import LayerHelper
from .initializer import Constant
from . import layers

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "Adam", "AdamOptimizer", "AdamW", "Adamax", "AdamaxOptimizer",
    "Adagrad", "AdagradOptimizer", "Adadelta", "AdadeltaOptimizer",
    "RMSProp", "RMSPropOptimizer", "Ftrl", "FtrlOptimizer", "Lamb",
    "LambOptimizer", "LarsMomentum", "LarsMomentumOptimizer",
    "DecayedAdagrad", "DecayedAdagradOptimizer", "DpsgdOptimizer",
    "ProximalGD", "ProximalGDOptimizer", "ProximalAdagrad",
    "ProximalAdagradOptimizer",
    "ExponentialMovingAverage", "L1Decay", "L2Decay",
    "GradientClipByValue", "GradientClipByNorm", "GradientClipByGlobalNorm",
    "gradient_merge",
]


# ---------------------------------------------------------------------------
# regularizers (fluid/regularizer.py)
# ---------------------------------------------------------------------------
class L2Decay:
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append(self, param, grad):
        return layers.elementwise_add(
            grad, layers.scale(param, scale=self.coeff))


class L1Decay:
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append(self, param, grad):
        sign = layers.cast(layers._binary_op("greater_than", param, 0.0),
                           param.dtype)
        neg = layers.cast(layers._binary_op("less_than", param, 0.0),
                          param.dtype)
        return layers.elementwise_add(
            grad, layers.scale(layers.elementwise_sub(sign, neg),
                               scale=self.coeff))


# ---------------------------------------------------------------------------
# gradient clipping (fluid/clip.py: GradientClipBy{Value,Norm,GlobalNorm})
# ---------------------------------------------------------------------------
class GradientClipByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, params_grads):
        return [(p, layers.clip(g, self.min, self.max))
                for p, g in params_grads]

    def _eager_apply(self, params_grads):
        import jax.numpy as jnp
        return [(p, jnp.clip(g, self.min, self.max))
                for p, g in params_grads]


class GradientClipByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params_grads):
        return [(p, layers.clip_by_norm(g, self.clip_norm))
                for p, g in params_grads]

    def _eager_apply(self, params_grads):
        import jax.numpy as jnp
        out = []
        for p, g in params_grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


class GradientClipByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params_grads):
        sq = [layers.reduce_sum(layers.square(g)) for _, g in params_grads]
        global_norm = layers.sqrt(layers.sums(sq))
        max_norm = layers.fill_constant([1], "float32", self.clip_norm)
        scale = layers.elementwise_div(
            max_norm,
            layers.elementwise_max(global_norm, max_norm))
        return [(p, layers.elementwise_mul(g, scale))
                for p, g in params_grads]

    def _eager_apply(self, params_grads):
        import jax.numpy as jnp
        if not params_grads:
            return params_grads
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for _, g in params_grads))
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        return [(p, (g.astype(jnp.float32) * scale).astype(g.dtype))
                for p, g in params_grads]


# ---------------------------------------------------------------------------
# base optimizer
# ---------------------------------------------------------------------------
class Optimizer:
    _op_type: str = None

    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self._regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or type(self).__name__
        self._lr_var: Optional[VarDesc] = None
        self._accumulators: Dict[str, Dict[str, VarDesc]] = {}
        self.helper = None

    # -- lr -----------------------------------------------------------------
    def _create_lr_var(self) -> VarDesc:
        if self._lr_var is not None:
            return self._lr_var
        lr = self._learning_rate
        if isinstance(lr, VarDesc):
            self._lr_var = lr
            return lr
        from ..optimizer.lr_scheduler import LRScheduler
        if isinstance(lr, LRScheduler):
            self._lr_var = lr._create_static_var()
            return self._lr_var
        self._lr_var = layers.create_global_var(
            [1], float(lr), "float32", persistable=True,
            name=unique_name("learning_rate"))
        return self._lr_var

    def set_lr(self, value, scope=None):
        """Dygraph/2.0-style runtime lr update: rewrite the scope value."""
        from .executor import global_scope
        import jax.numpy as jnp
        scope = scope or global_scope()
        if self._lr_var is not None:
            scope.set(self._lr_var.name, jnp.asarray([float(value)],
                                                     jnp.float32))
        self._learning_rate = float(value)

    def get_lr(self):
        return self._learning_rate

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        acc = self._accumulators.setdefault(name, {})
        if param.name in acc:
            return acc[param.name]
        helper = LayerHelper(self._name)
        v = helper.main_program.global_block().create_var(
            name=unique_name(f"{param.name}_{name}"),
            shape=shape or param.shape,
            dtype=dtype or "float32", persistable=True, stop_gradient=True)
        # explicit accumulator→param link so sharding inheritance
        # (compiled_program state_specs) never guesses from name prefixes
        v.attrs["accum_of"] = param.name
        Constant(fill_value)(v, helper.startup_program.global_block())
        acc[param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- API ----------------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list or self._parameter_list,
                               no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        """fluid optimizer.py:802 — clip, regularize, then per-param op.
        Ops go into the *loss's* program (the reference guards on it,
        optimizer.py:908 program_guard), not whatever default is current."""
        from ..core.program import program_guard, default_startup_program
        if params_grads:
            program = params_grads[0][0].block.program
        else:
            program = default_main_program()
        with program_guard(program), \
                program._op_role_guard(OpRole.Optimize):
            if self._grad_clip is not None:
                params_grads = self._grad_clip.apply(params_grads)
            if self._regularization is not None:
                params_grads = [(p, self._regularization.append(p, g))
                                for p, g in params_grads]
            lr = self._create_lr_var()
            ops = []
            for p, g in params_grads:
                ops.append(self._append_optimize_op(p, g, lr))
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        ops = self.apply_gradients(params_grads)
        # recorded for the PS transpiler (DistributeTranspiler reads the
        # param/grad pairing off the program, transpiler flow parity)
        loss.block.program._ps_params_grads = params_grads
        return ops, params_grads

    def _append_optimize_op(self, param, grad, lr):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, param, grad, lr):
        helper = LayerHelper("sgd")
        return helper.append_op(
            "sgd",
            inputs={"Param": param, "Grad": grad, "LearningRate": lr},
            outputs={"ParamOut": param})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, param, grad, lr):
        vel = self._add_accumulator("velocity", param)
        helper = LayerHelper("momentum")
        return helper.append_op(
            "momentum",
            inputs={"Param": param, "Grad": grad, "Velocity": vel,
                    "LearningRate": lr},
            outputs={"ParamOut": param, "VelocityOut": vel},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _append_optimize_op(self, param, grad, lr):
        vel = self._add_accumulator("velocity", param)
        helper = LayerHelper("lars_momentum")
        return helper.append_op(
            "lars_momentum",
            inputs={"Param": param, "Grad": grad, "Velocity": vel,
                    "LearningRate": lr},
            outputs={"ParamOut": param, "VelocityOut": vel},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _op = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, param, grad, lr):
        m1 = self._add_accumulator("moment1", param)
        m2 = self._add_accumulator("moment2", param)
        b1p = self._add_accumulator("beta1_pow", param, self._beta1,
                                    shape=[1])
        b2p = self._add_accumulator("beta2_pow", param, self._beta2,
                                    shape=[1])
        helper = LayerHelper(self._op)
        return helper.append_op(
            self._op,
            inputs={"Param": param, "Grad": grad, "LearningRate": lr,
                    "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p,
                    "Beta2Pow": b2p},
            outputs={"ParamOut": param, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamW(AdamOptimizer):
    _op = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, apply_decay_param_fun=None,
                 **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self._coeff = weight_decay
        self._decay_fn = apply_decay_param_fun

    def _append_optimize_op(self, param, grad, lr):
        if self._decay_fn is not None and not self._decay_fn(param.name):
            # fall back to plain adam for excluded params
            saved, self._op = self._op, "adam"
            try:
                return super()._append_optimize_op(param, grad, lr)
            finally:
                self._op = saved
        op = super()._append_optimize_op(param, grad, lr)
        op.attrs["coeff"] = self._coeff
        return op


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, param, grad, lr):
        m = self._add_accumulator("moment", param)
        inf_norm = self._add_accumulator("inf_norm", param)
        b1p = self._add_accumulator("beta1_pow", param, self._beta1, [1])
        helper = LayerHelper("adamax")
        return helper.append_op(
            "adamax",
            inputs={"Param": param, "Grad": grad, "LearningRate": lr,
                    "Moment": m, "InfNorm": inf_norm, "Beta1Pow": b1p},
            outputs={"ParamOut": param, "MomentOut": m,
                     "InfNormOut": inf_norm, "Beta1PowOut": b1p},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _append_optimize_op(self, param, grad, lr):
        moment = self._add_accumulator("moment", param, self._init_acc)
        helper = LayerHelper("adagrad")
        return helper.append_op(
            "adagrad",
            inputs={"Param": param, "Grad": grad, "Moment": moment,
                    "LearningRate": lr},
            outputs={"ParamOut": param, "MomentOut": moment},
            attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _append_optimize_op(self, param, grad, lr):
        moment = self._add_accumulator("moment", param)
        helper = LayerHelper("decayed_adagrad")
        return helper.append_op(
            "decayed_adagrad",
            inputs={"Param": param, "Grad": grad, "Moment": moment,
                    "LearningRate": lr},
            outputs={"ParamOut": param, "MomentOut": moment},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _append_optimize_op(self, param, grad, lr):
        avg_sq_g = self._add_accumulator("avg_squared_grad", param)
        avg_sq_u = self._add_accumulator("avg_squared_update", param)
        helper = LayerHelper("adadelta")
        return helper.append_op(
            "adadelta",
            inputs={"Param": param, "Grad": grad,
                    "AvgSquaredGrad": avg_sq_g,
                    "AvgSquaredUpdate": avg_sq_u},
            outputs={"ParamOut": param, "AvgSquaredGradOut": avg_sq_g,
                     "AvgSquaredUpdateOut": avg_sq_u},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _append_optimize_op(self, param, grad, lr):
        ms = self._add_accumulator("mean_square", param)
        mg = self._add_accumulator("mean_grad", param)
        mom = self._add_accumulator("momentum", param)
        helper = LayerHelper("rmsprop")
        return helper.append_op(
            "rmsprop",
            inputs={"Param": param, "Grad": grad, "MeanSquare": ms,
                    "MeanGrad": mg, "Moment": mom, "LearningRate": lr},
            outputs={"ParamOut": param, "MeanSquareOut": ms,
                     "MeanGradOut": mg, "MomentOut": mom},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class ProximalGDOptimizer(Optimizer):
    """fluid.optimizer.ProximalGDOptimizer (proximal_gd_op.h) — proximal
    gradient descent with l1/l2 regularization folded into the step."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _append_optimize_op(self, param, grad, lr):
        helper = LayerHelper("proximal_gd")
        return helper.append_op(
            "proximal_gd",
            inputs={"Param": param, "Grad": grad, "LearningRate": lr},
            outputs={"ParamOut": param},
            attrs={"l1": self._l1, "l2": self._l2})


class ProximalAdagradOptimizer(Optimizer):
    """fluid.optimizer.ProximalAdagradOptimizer (proximal_adagrad_op.h)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2 = l1, l2

    def _append_optimize_op(self, param, grad, lr):
        moment = self._add_accumulator("moment", param)
        helper = LayerHelper("proximal_adagrad")
        return helper.append_op(
            "proximal_adagrad",
            inputs={"Param": param, "Moment": moment, "Grad": grad,
                    "LearningRate": lr},
            outputs={"ParamOut": param, "MomentOut": moment},
            attrs={"l1": self._l1, "l2": self._l2})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _append_optimize_op(self, param, grad, lr):
        sq = self._add_accumulator("squared", param)
        lin = self._add_accumulator("linear", param)
        helper = LayerHelper("ftrl")
        return helper.append_op(
            "ftrl",
            inputs={"Param": param, "Grad": grad, "SquaredAccumulator": sq,
                    "LinearAccumulator": lin, "LearningRate": lr},
            outputs={"ParamOut": param, "SquaredAccumOut": sq,
                     "LinearAccumOut": lin},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class LambOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, **kw)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, param, grad, lr):
        m1 = self._add_accumulator("moment1", param)
        m2 = self._add_accumulator("moment2", param)
        b1p = self._add_accumulator("beta1_pow", param, self._beta1, [1])
        b2p = self._add_accumulator("beta2_pow", param, self._beta2, [1])
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(param.name):
            wd = 0.0
        helper = LayerHelper("lamb")
        return helper.append_op(
            "lamb",
            inputs={"Param": param, "Grad": grad, "LearningRate": lr,
                    "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p,
                    "Beta2Pow": b2p},
            outputs={"ParamOut": param, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd})


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=0.9, batch_size=0.999, sigma=1e-8,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, param, grad, lr):
        helper = LayerHelper("dpsgd")
        return helper.append_op(
            "dpsgd",
            inputs={"Param": param, "Grad": grad, "LearningRate": lr},
            outputs={"ParamOut": param},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma})


class ExponentialMovingAverage:
    """EMA of parameters (fluid optimizer.py ExponentialMovingAverage):
    shadow vars updated by in-graph ops; apply()/restore() swap params."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadows: List[Tuple[VarDesc, VarDesc]] = []

    def update(self):
        program = default_main_program()
        helper = LayerHelper(self._name)
        with program._op_role_guard(OpRole.Optimize):
            for p in program.all_parameters():
                if not p.trainable:
                    continue
                shadow = helper.main_program.global_block().create_var(
                    name=unique_name(f"{p.name}_ema"), shape=p.shape,
                    dtype=p.dtype, persistable=True, stop_gradient=True)
                Constant(0.0)(shadow,
                              helper.startup_program.global_block())
                new_shadow = layers.elementwise_add(
                    layers.scale(shadow, scale=self._decay),
                    layers.scale(p, scale=1.0 - self._decay))
                helper.append_op("assign", inputs={"X": new_shadow},
                                 outputs={"Out": shadow})
                self._shadows.append((p, shadow))

    def apply(self, executor, need_restore=True):
        from .executor import global_scope
        scope = global_scope()
        self._backup = {}
        for p, s in self._shadows:
            self._backup[p.name] = scope.get(p.name)
            if scope.get(s.name) is not None:
                scope.set(p.name, scope.get(s.name))

    def restore(self, executor):
        from .executor import global_scope
        scope = global_scope()
        for name, v in self._backup.items():
            scope.set(name, v)


class ModelAverage:
    """Accumulated parameter averaging (fluid optimizer.py ModelAverage,
    backed by the average_accumulates op): train-time ops maintain
    windowed parameter sums; apply()/restore() swap the averaged
    parameters in for evaluation."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, name=None):
        self._window_rate = float(average_window_rate)
        self._min_window = int(min_average_window)
        self._max_window = int(max_average_window)
        self._name = name or "model_average"
        self._accs: List[Tuple[VarDesc, Dict[str, VarDesc]]] = []
        program = default_main_program()
        helper = LayerHelper(self._name)
        block = program.global_block()
        with program._op_role_guard(OpRole.Optimize):
            for p in program.all_parameters():
                if not p.trainable:
                    continue
                acc = {}
                for key, shape, dtype in (
                        ("sum_1", p.shape, p.dtype),
                        ("sum_2", p.shape, p.dtype),
                        ("sum_3", p.shape, p.dtype),
                        ("num_accumulates", (1,), "int64"),
                        ("old_num_accumulates", (1,), "int64"),
                        ("num_updates", (1,), "int64")):
                    v = block.create_var(
                        name=unique_name(f"{p.name}_avg_{key}"),
                        shape=shape, dtype=dtype, persistable=True,
                        stop_gradient=True)
                    Constant(0.0)(v, helper.startup_program.global_block())
                    acc[key] = v
                helper.append_op(
                    "average_accumulates",
                    inputs={"param": p, "in_sum_1": acc["sum_1"],
                            "in_sum_2": acc["sum_2"],
                            "in_sum_3": acc["sum_3"],
                            "in_num_accumulates": acc["num_accumulates"],
                            "in_old_num_accumulates":
                                acc["old_num_accumulates"],
                            "in_num_updates": acc["num_updates"]},
                    outputs={"out_sum_1": acc["sum_1"],
                             "out_sum_2": acc["sum_2"],
                             "out_sum_3": acc["sum_3"],
                             "out_num_accumulates":
                                 acc["num_accumulates"],
                             "out_old_num_accumulates":
                                 acc["old_num_accumulates"],
                             "out_num_updates": acc["num_updates"]},
                    attrs={"average_window": self._window_rate,
                           "min_average_window": self._min_window,
                           "max_average_window": self._max_window})
                self._accs.append((p, acc))

    def apply(self, executor=None, need_restore=True):
        """Swap averaged parameters in IMMEDIATELY and return a context
        handle, so both fluid idioms work:
        `with ma.apply(exe): evaluate()` (restores on exit when
        need_restore) and the imperative `ma.apply(exe) ...
        ma.restore(exe)`."""
        import numpy as np
        from .executor import global_scope
        scope = global_scope()
        self._backup = {}
        for p, acc in self._accs:
            vals = {k: np.asarray(scope.get(v.name))
                    for k, v in acc.items() if scope.get(v.name) is not None}
            if "sum_1" not in vals:
                continue
            total = (vals["sum_1"] + vals.get("sum_2", 0)
                     + vals.get("sum_3", 0))
            count = float(vals.get("num_accumulates", np.ones(1))[0]
                          + vals.get("old_num_accumulates",
                                     np.zeros(1))[0])
            if count <= 0:
                continue
            self._backup[p.name] = scope.get(p.name)
            scope.set(p.name, (total / count).astype(total.dtype))
        return _ModelAverageApplied(self, need_restore)

    def restore(self, executor=None):
        from .executor import global_scope
        scope = global_scope()
        for name, v in getattr(self, "_backup", {}).items():
            scope.set(name, v)


class _ModelAverageApplied:
    def __init__(self, ma, need_restore):
        self._ma, self._need_restore = ma, need_restore

    def __enter__(self):
        return self._ma

    def __exit__(self, *exc):
        if self._need_restore:
            self._ma.restore()
        return False


class LookaheadOptimizer:
    """Lookahead wrapper (fluid optimizer.py LookaheadOptimizer,
    arXiv:1907.08610): the inner optimizer advances fast weights every
    step; every k steps the slow copies move alpha toward the fast
    weights and the fast weights reset to them.  The k-periodic sync is
    expressed with mask arithmetic (cond-free, XLA-friendly):
    slow' = slow + m*alpha*(fast-slow); fast' = m*slow' + (1-m)*fast."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import layers
        result = self.inner_optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        program = default_main_program()
        helper = LayerHelper("lookahead")
        block = program.global_block()
        startup = helper.startup_program.global_block()
        # only the parameters the inner optimizer actually trains get
        # slow copies — untouched params would just burn memory and
        # per-step ops computing fast==fast
        trained = None
        if isinstance(result, tuple) and len(result) == 2:
            trained = {p.name for p, _ in result[1]}
        elif parameter_list is not None:
            trained = {p.name if hasattr(p, "name") else str(p)
                       for p in parameter_list}
        with program._op_role_guard(OpRole.Optimize):
            # int64 counter: a float32 step would stop counting at 2^24
            # (16.8M steps) and freeze the periodic sync forever
            step = block.create_var(name=unique_name("lookahead_step"),
                                    shape=(1,), dtype="int64",
                                    persistable=True, stop_gradient=True)
            Constant(0.0)(step, startup)
            helper.append_op("increment", inputs={"X": step},
                             outputs={"Out": step},
                             attrs={"step": 1.0})
            ki = layers.fill_constant((1,), "int64", self.k)
            mod = layers.elementwise_mod(step, ki)
            mask = layers.cast(
                layers.equal(mod, layers.fill_constant((1,), "int64", 0)),
                "float32")
            for p in program.all_parameters():
                if not p.trainable:
                    continue
                if trained is not None and p.name not in trained:
                    continue
                slow = block.create_var(
                    name=unique_name(f"{p.name}_slow"), shape=p.shape,
                    dtype=p.dtype, persistable=True, stop_gradient=True)
                # slow weights start AT the initial fast weights: declare
                # the var in the startup block too (the startup run only
                # persists vars the startup program itself declares)
                startup.create_var(name=slow.name, shape=p.shape,
                                   dtype=p.dtype, persistable=True,
                                   stop_gradient=True)
                # scale(1.0) rather than assign: assign would ALIAS the
                # param's buffer in the scope and the jitted step donates
                # state buffers — the same buffer donated twice is an
                # XLA execution error
                startup.append_op("scale", inputs={"X": [p.name]},
                                  outputs={"Out": [slow.name]},
                                  attrs={"scale": 1.0, "bias": 0.0})
                diff = layers.elementwise_sub(p, slow)
                new_slow = layers.elementwise_add(
                    slow, layers.elementwise_mul(
                        layers.scale(diff, scale=self.alpha), mask))
                new_fast = layers.elementwise_add(
                    layers.elementwise_mul(new_slow, mask),
                    layers.elementwise_mul(
                        p, layers.scale(mask, scale=-1.0, bias=1.0)))
                helper.append_op("assign", inputs={"X": new_slow},
                                 outputs={"Out": slow})
                helper.append_op("assign", inputs={"X": new_fast},
                                 outputs={"Out": p})
        return result


def gradient_merge(program, k_steps, startup_program=None,
                   params_grads=None, avg=True):
    """Standalone k-step gradient accumulation over an already-minimized
    `program` — the GradientMergeOptimizer rewrite without the fleet
    strategy detour: grads accumulate into PERSISTABLE buffers every
    step and the optimizer ops commit through a step-counter mask on the
    k-th (straight-line masked update; one XLA computation, see
    distributed/fleet/meta_optimizers/gradient_merge_optimizer.py).

    The accumulators and the step counter are persistable and
    startup-initialized, so they thread through `Executor.run_steps`'
    donated on-device state and ride checkpoints
    (`Executor.checkpoint_snapshot`) like any optimizer accumulator —
    a resumed run continues mid-accumulation-window.

    `params_grads` defaults to the pairs `minimize()` recorded on the
    program; pass them explicitly when composing with wrappers that do
    not record them (e.g. amp.decorate's minimize)."""
    from ..core.program import default_startup_program
    if k_steps is None or int(k_steps) <= 1:
        return program
    pgs = params_grads or getattr(program, "_ps_params_grads", None)
    if not pgs:
        raise ValueError(
            "gradient_merge: run optimizer.minimize(loss) on the program "
            "first (it records the param/grad pairs), or pass "
            "params_grads= explicitly")
    startup = startup_program or default_startup_program()
    from ..distributed.fleet.meta_optimizers.gradient_merge_optimizer \
        import apply_gradient_merge
    apply_gradient_merge(program, startup, pgs, int(k_steps), avg=avg)
    return program


class RecomputeOptimizer(Optimizer):
    """Activation-checkpointing wrapper (fluid optimizer.py:4458): backward
    replays forward segments from user checkpoints (see recompute_rewrite)."""

    def __init__(self, optimizer: Optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        assert self._checkpoints is not None, \
            "call _set_checkpoints before minimize (fluid contract)"
        return append_backward(loss, parameter_list, no_grad_set,
                               checkpoints=self._checkpoints)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        ops = self.apply_gradients(params_grads)
        return ops, params_grads


# 2.0-style short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Dpsgd = DpsgdOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer

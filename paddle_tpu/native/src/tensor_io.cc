// Combined tensor file serialization — the C++ checkpoint fast path.
//
// Reference: /root/reference/paddle/fluid/framework/save_load_util.cc
// (version header + per-tensor proto + raw bytes; save_combine /
// load_combine ops).  TPU-native role: big checkpoint files stream through
// C++ fwrite/fread with CRC32 integrity, off the Python allocator.
//
// File format "PTNT0001" (little-endian):
//   magic[8]
//   u32 n_tensors
//   per tensor:
//     u32 name_len, name bytes
//     u32 dtype_len, dtype bytes        (numpy dtype str, e.g. "float32")
//     u32 ndim, i64 dims[ndim]
//     u64 nbytes, raw bytes
//     u32 crc32(raw)
//
// C ABI: writer builds the file in one pass; reader exposes an iterator.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[8] = {'P', 'T', 'N', 'T', '0', '0', '0', '1'};

uint32_t Crc32(const unsigned char* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

template <typename T>
bool WriteOne(FILE* f, T v) {
  return std::fwrite(&v, sizeof(T), 1, f) == 1;
}

template <typename T>
bool ReadOne(FILE* f, T* v) {
  return std::fread(v, sizeof(T), 1, f) == 1;
}

struct Reader {
  FILE* f = nullptr;
  uint32_t n = 0;
  uint32_t next = 0;
  std::string name, dtype;
  std::vector<int64_t> dims;
  std::vector<char> data;
  std::string error;
};

}  // namespace

extern "C" {

// ---- writer ---------------------------------------------------------------
// returns 0 on success, negative on error
int ptio_save(const char* path, int n, const char** names,
              const char** dtypes, const int* ndims,
              const int64_t* dims_flat, const uint64_t* nbytes,
              const char** data) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int rc = 0;
  do {
    if (std::fwrite(kMagic, 1, 8, f) != 8) { rc = -2; break; }
    if (!WriteOne<uint32_t>(f, static_cast<uint32_t>(n))) { rc = -2; break; }
    const int64_t* dp = dims_flat;
    for (int i = 0; i < n && rc == 0; i++) {
      uint32_t nl = std::strlen(names[i]);
      uint32_t dl = std::strlen(dtypes[i]);
      if (!WriteOne(f, nl) || std::fwrite(names[i], 1, nl, f) != nl ||
          !WriteOne(f, dl) || std::fwrite(dtypes[i], 1, dl, f) != dl ||
          !WriteOne<uint32_t>(f, static_cast<uint32_t>(ndims[i]))) {
        rc = -2; break;
      }
      for (int d = 0; d < ndims[i]; d++)
        if (!WriteOne<int64_t>(f, *dp++)) { rc = -2; break; }
      if (rc) break;
      if (!WriteOne<uint64_t>(f, nbytes[i]) ||
          std::fwrite(data[i], 1, nbytes[i], f) != nbytes[i] ||
          !WriteOne<uint32_t>(
              f, Crc32(reinterpret_cast<const unsigned char*>(data[i]),
                       nbytes[i]))) {
        rc = -2; break;
      }
    }
  } while (false);
  std::fclose(f);
  return rc;
}

// ---- reader ---------------------------------------------------------------
void* ptio_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (std::fread(magic, 1, 8, f) != 8 ||
      std::memcmp(magic, kMagic, 8) != 0) {
    std::fclose(f);
    return nullptr;
  }
  Reader* r = new Reader;
  r->f = f;
  if (!ReadOne(f, &r->n)) {
    std::fclose(f);
    delete r;
    return nullptr;
  }
  return r;
}

uint32_t ptio_count(void* h) { return static_cast<Reader*>(h)->n; }

// advance to the next tensor; 1 = ok, 0 = end, negative = error/corrupt
int ptio_next(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->next >= r->n) return 0;
  uint32_t nl, dl, nd, crc;
  uint64_t nb;
  if (!ReadOne(r->f, &nl)) return -2;
  r->name.resize(nl);
  if (nl && std::fread(&r->name[0], 1, nl, r->f) != nl) return -2;
  if (!ReadOne(r->f, &dl)) return -2;
  r->dtype.resize(dl);
  if (dl && std::fread(&r->dtype[0], 1, dl, r->f) != dl) return -2;
  if (!ReadOne(r->f, &nd)) return -2;
  r->dims.resize(nd);
  for (uint32_t i = 0; i < nd; i++)
    if (!ReadOne(r->f, &r->dims[i])) return -2;
  if (!ReadOne(r->f, &nb)) return -2;
  r->data.resize(nb);
  if (nb && std::fread(r->data.data(), 1, nb, r->f) != nb) return -2;
  if (!ReadOne(r->f, &crc)) return -2;
  if (crc != Crc32(reinterpret_cast<unsigned char*>(r->data.data()), nb))
    return -3;  // corruption detected
  r->next++;
  return 1;
}

const char* ptio_name(void* h) { return static_cast<Reader*>(h)->name.c_str(); }
const char* ptio_dtype(void* h) {
  return static_cast<Reader*>(h)->dtype.c_str();
}
uint32_t ptio_ndim(void* h) {
  return static_cast<uint32_t>(static_cast<Reader*>(h)->dims.size());
}
const int64_t* ptio_dims(void* h) {
  return static_cast<Reader*>(h)->dims.data();
}
uint64_t ptio_nbytes(void* h) {
  return static_cast<Reader*>(h)->data.size();
}
const char* ptio_data(void* h) { return static_cast<Reader*>(h)->data.data(); }

void ptio_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->f) std::fclose(r->f);
  delete r;
}

}  // extern "C"

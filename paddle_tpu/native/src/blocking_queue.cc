// Blocking MPMC byte-buffer queue — the C++ core of the data pipeline.
//
// Reference: /root/reference/paddle/fluid/framework/blocking_queue.h
// (mutex+condvar bounded queue used by readers/executors) and
// operators/reader/buffered_reader (double-buffered prefetch).  TPU-native
// role: host-side feed pipeline buffering between dataloader workers and
// the device feed, off the Python GIL.
//
// C ABI (ctypes-consumed; all buffers are copied in, malloc'd out):
//   ptq_create(capacity)            -> queue*
//   ptq_push(q, data, len, t_ms)    -> 0 ok | -1 timeout | -2 closed
//   ptq_pop(q, &out, t_ms)          -> len>=0 | -1 timeout | -2 closed+empty
//   ptq_free_buf(p)                 free a popped buffer
//   ptq_close(q)                    wake all, no further pushes
//   ptq_size(q) / ptq_capacity(q)
//   ptq_destroy(q)
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <utility>
#include <vector>

namespace {

struct Buf {
  char* data;
  size_t len;
};

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t cap) : cap_(cap ? cap : 1) {}

  ~BlockingQueue() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& b : q_) delete[] b.data;
    q_.clear();
  }

  int Push(const char* data, size_t len, int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [this] { return closed_ || q_.size() < cap_; };
    if (timeout_ms < 0) {
      not_full_.wait(lk, pred);
    } else if (!not_full_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
      return -1;
    }
    if (closed_) return -2;
    char* copy = new char[len ? len : 1];
    std::memcpy(copy, data, len);
    q_.push_back({copy, len});
    not_empty_.notify_one();
    return 0;
  }

  long long Pop(char** out, int timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [this] { return closed_ || !q_.empty(); };
    if (timeout_ms < 0) {
      not_empty_.wait(lk, pred);
    } else if (!not_empty_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
      return -1;
    }
    if (q_.empty()) return -2;  // closed and drained
    Buf b = q_.front();
    q_.pop_front();
    not_full_.notify_one();
    *out = b.data;
    return static_cast<long long>(b.len);
  }

  void Close() {
    std::lock_guard<std::mutex> g(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> g(mu_);
    return q_.size();
  }

  size_t Capacity() const { return cap_; }

  bool Closed() {
    std::lock_guard<std::mutex> g(mu_);
    return closed_;
  }

 private:
  const size_t cap_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<Buf> q_;
  bool closed_ = false;
};

}  // namespace

extern "C" {

void* ptq_create(size_t capacity) { return new BlockingQueue(capacity); }

int ptq_push(void* q, const char* data, size_t len, int timeout_ms) {
  return static_cast<BlockingQueue*>(q)->Push(data, len, timeout_ms);
}

long long ptq_pop(void* q, char** out, int timeout_ms) {
  return static_cast<BlockingQueue*>(q)->Pop(out, timeout_ms);
}

void ptq_free_buf(char* p) { delete[] p; }

void ptq_close(void* q) { static_cast<BlockingQueue*>(q)->Close(); }

size_t ptq_size(void* q) { return static_cast<BlockingQueue*>(q)->Size(); }

size_t ptq_capacity(void* q) {
  return static_cast<BlockingQueue*>(q)->Capacity();
}

int ptq_closed(void* q) {
  return static_cast<BlockingQueue*>(q)->Closed() ? 1 : 0;
}

void ptq_destroy(void* q) { delete static_cast<BlockingQueue*>(q); }

}  // extern "C"

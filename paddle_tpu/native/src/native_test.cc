// Native-layer C++ tests (C27 — the reference's C++ test tier,
// paddle/fluid/framework/*_test.cc style).  Self-contained assert-based
// runner: links blocking_queue.cc + tensor_io.cc directly and exercises
// their C ABI from C++ — push/pop/timeout/close across threads, and a
// tensor-file round trip with CRC verification — so the native pieces
// are tested below the Python bindings, not only through them.
//
// Built + run by tests/test_native_cpp.py:
//   g++ -O1 -std=c++17 native_test.cc blocking_queue.cc tensor_io.cc \
//       tensor_io.cc -o native_test && ./native_test
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
// blocking_queue.cc ABI (must match blocking_queue.cc:105 exactly —
// mismatched extern "C" declarations across TUs are ill-formed)
void* ptq_create(size_t capacity);
int ptq_push(void* q, const char* data, size_t len, int timeout_ms);
long long ptq_pop(void* q, char** out, int timeout_ms);
void ptq_free_buf(char* p);
void ptq_close(void* q);
size_t ptq_size(void* q);
size_t ptq_capacity(void* q);
void ptq_destroy(void* q);
// tensor_io.cc ABI (tensor_io.cc:73)
int ptio_save(const char* path, int n, const char** names,
              const char** dtypes, const int* ndims,
              const int64_t* dims_flat, const uint64_t* nbytes,
              const char** datas);
void* ptio_open(const char* path);
uint32_t ptio_count(void* h);
int ptio_next(void* h);
const char* ptio_name(void* h);
const char* ptio_dtype(void* h);
uint32_t ptio_ndim(void* h);
const int64_t* ptio_dims(void* h);
uint64_t ptio_nbytes(void* h);
const char* ptio_data(void* h);
void ptio_close(void* h);
}

static int failures = 0;
#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,     \
                   #cond);                                             \
      failures++;                                                      \
    }                                                                  \
  } while (0)

static void test_queue_fifo_and_timeout() {
  void* q = ptq_create(2);
  CHECK(ptq_capacity(q) == 2);
  CHECK(ptq_push(q, "aa", 2, 100) == 0);
  CHECK(ptq_push(q, "bbb", 3, 100) == 0);
  // full queue: bounded push times out instead of blocking forever
  CHECK(ptq_push(q, "cc", 2, 50) == -1);
  char* out = nullptr;
  long long n = ptq_pop(q, &out, 100);
  CHECK(n == 2 && std::memcmp(out, "aa", 2) == 0);
  ptq_free_buf(out);
  n = ptq_pop(q, &out, 100);
  CHECK(n == 3 && std::memcmp(out, "bbb", 3) == 0);
  ptq_free_buf(out);
  // empty queue: pop times out
  CHECK(ptq_pop(q, &out, 50) == -1);
  ptq_destroy(q);
}

static void test_queue_cross_thread_and_close() {
  void* q = ptq_create(4);
  const int kMsgs = 200;
  std::thread producer([q] {
    for (int i = 0; i < kMsgs; i++) {
      std::string m = "msg" + std::to_string(i);
      while (ptq_push(q, m.data(), m.size(), 1000) != 0) {
      }
    }
    ptq_close(q);
  });
  int received = 0;
  for (;;) {
    char* out = nullptr;
    long long n = ptq_pop(q, &out, 2000);
    if (n == -2) break;  // closed + drained
    CHECK(n > 0);
    if (n <= 0) break;   // timeout: FAIL recorded above, don't deref
    std::string m(out, out + n);
    CHECK(m == "msg" + std::to_string(received));
    ptq_free_buf(out);
    received++;
  }
  producer.join();
  CHECK(received == kMsgs);
  // closed queue refuses further pushes
  CHECK(ptq_push(q, "x", 1, 10) == -2);
  ptq_destroy(q);
}

static void test_tensor_io_round_trip(const char* path) {
  std::vector<float> a = {1.5f, -2.0f, 3.25f, 0.0f};
  std::vector<int64_t> b = {7, -9};
  const char* names[] = {"w0", "ids"};
  const char* dtypes[] = {"float32", "int64"};
  int ndims[] = {2, 1};
  int64_t dims_flat[] = {2, 2, 2};
  uint64_t nbytes[] = {a.size() * sizeof(float),
                       b.size() * sizeof(int64_t)};
  const char* datas[] = {reinterpret_cast<const char*>(a.data()),
                         reinterpret_cast<const char*>(b.data())};
  CHECK(ptio_save(path, 2, names, dtypes, ndims, dims_flat, nbytes,
                  datas) == 0);

  void* h = ptio_open(path);
  CHECK(h != nullptr);
  CHECK(ptio_count(h) == 2);
  CHECK(ptio_next(h) == 1);  // 1 = advanced, 0 = end, <0 = corrupt
  CHECK(std::string(ptio_name(h)) == "w0");
  CHECK(std::string(ptio_dtype(h)) == "float32");
  CHECK(ptio_ndim(h) == 2);
  CHECK(ptio_dims(h)[0] == 2 && ptio_dims(h)[1] == 2);
  CHECK(ptio_nbytes(h) == nbytes[0]);
  CHECK(std::memcmp(ptio_data(h), a.data(), nbytes[0]) == 0);
  CHECK(ptio_next(h) == 1);
  CHECK(std::string(ptio_name(h)) == "ids");
  CHECK(std::memcmp(ptio_data(h), b.data(), nbytes[1]) == 0);
  ptio_close(h);

  // corrupt one payload byte: the CRC check must reject the tensor
  std::FILE* f = std::fopen(path, "r+b");
  CHECK(f != nullptr);
  std::fseek(f, -6, SEEK_END);  // inside the last tensor's raw bytes
  std::fputc(0x5A, f);
  std::fclose(f);
  h = ptio_open(path);
  CHECK(h != nullptr);
  CHECK(ptio_next(h) == 1);        // first tensor still intact
  CHECK(ptio_next(h) == -3);       // corrupted one fails CRC
  ptio_close(h);
  std::remove(path);
}

int main(int argc, char** argv) {
  const char* tmp = argc > 1 ? argv[1] : "/tmp/ptnt_native_test.bin";
  test_queue_fifo_and_timeout();
  test_queue_cross_thread_and_close();
  test_tensor_io_round_trip(tmp);
  if (failures) {
    std::fprintf(stderr, "%d native test failures\n", failures);
    return 1;
  }
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}

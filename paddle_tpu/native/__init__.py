"""Native (C++) runtime components, ctypes-bound.

Reference parity: the reference's runtime around the compute path is C++
(framework/blocking_queue.h, save_load_util.cc, buffered_reader) — these
are the TPU-native equivalents.  Compiled on first import with g++ into a
per-repo cache; every consumer has a pure-Python fallback, so the package
works (slower) without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "BlockingQueue", "save_tensors", "load_tensors",
           "lib"]

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__),
                         "libpaddle_tpu_native.so")
_SOURCES = ["blocking_queue.cc", "tensor_io.cc"]

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    newest = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(_LIB_PATH) and \
            os.path.getmtime(_LIB_PATH) >= newest:
        return True
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _LIB_PATH] + srcs
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=120)
    except (FileNotFoundError, subprocess.TimeoutExpired):
        return False
    return res.returncode == 0 and os.path.exists(_LIB_PATH)


def _bind(lib):
    c = ctypes
    lib.ptq_create.restype = c.c_void_p
    lib.ptq_create.argtypes = [c.c_size_t]
    lib.ptq_push.restype = c.c_int
    lib.ptq_push.argtypes = [c.c_void_p, c.c_char_p, c.c_size_t, c.c_int]
    lib.ptq_pop.restype = c.c_longlong
    lib.ptq_pop.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_char)),
                            c.c_int]
    lib.ptq_free_buf.argtypes = [c.POINTER(c.c_char)]
    lib.ptq_close.argtypes = [c.c_void_p]
    lib.ptq_size.restype = c.c_size_t
    lib.ptq_size.argtypes = [c.c_void_p]
    lib.ptq_capacity.restype = c.c_size_t
    lib.ptq_capacity.argtypes = [c.c_void_p]
    lib.ptq_closed.restype = c.c_int
    lib.ptq_closed.argtypes = [c.c_void_p]
    lib.ptq_destroy.argtypes = [c.c_void_p]

    lib.ptio_save.restype = c.c_int
    lib.ptio_save.argtypes = [
        c.c_char_p, c.c_int, c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.c_int), c.POINTER(c.c_int64),
        c.POINTER(c.c_uint64), c.POINTER(c.c_char_p)]
    lib.ptio_open.restype = c.c_void_p
    lib.ptio_open.argtypes = [c.c_char_p]
    lib.ptio_count.restype = c.c_uint32
    lib.ptio_count.argtypes = [c.c_void_p]
    lib.ptio_next.restype = c.c_int
    lib.ptio_next.argtypes = [c.c_void_p]
    lib.ptio_name.restype = c.c_char_p
    lib.ptio_name.argtypes = [c.c_void_p]
    lib.ptio_dtype.restype = c.c_char_p
    lib.ptio_dtype.argtypes = [c.c_void_p]
    lib.ptio_ndim.restype = c.c_uint32
    lib.ptio_ndim.argtypes = [c.c_void_p]
    lib.ptio_dims.restype = c.POINTER(c.c_int64)
    lib.ptio_dims.argtypes = [c.c_void_p]
    lib.ptio_nbytes.restype = c.c_uint64
    lib.ptio_nbytes.argtypes = [c.c_void_p]
    lib.ptio_data.restype = c.c_void_p
    lib.ptio_data.argtypes = [c.c_void_p]
    lib.ptio_close.argtypes = [c.c_void_p]
    return lib


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            return None
        if not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return lib() is not None


# ---------------------------------------------------------------------------
# BlockingQueue (framework/blocking_queue.h analog)
# ---------------------------------------------------------------------------
class BlockingQueue:
    """Bounded byte-buffer queue backed by the C++ core; holds bytes
    objects (callers pickle batches)."""

    def __init__(self, capacity: int = 8):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._lib = L
        self._q = L.ptq_create(capacity)

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        rc = self._lib.ptq_push(self._q, data, len(data), timeout_ms)
        if rc == -2:
            raise RuntimeError("queue closed")
        return rc == 0

    def pop(self, timeout_ms: int = -1):
        """Returns bytes, or None on timeout; raises EOFError when closed
        and drained."""
        out = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.ptq_pop(self._q, ctypes.byref(out), timeout_ms)
        if n == -1:
            return None
        if n == -2:
            raise EOFError("queue closed")
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.ptq_free_buf(out)

    def close(self):
        if self._q:
            self._lib.ptq_close(self._q)

    def size(self) -> int:
        return int(self._lib.ptq_size(self._q))

    def capacity(self) -> int:
        return int(self._lib.ptq_capacity(self._q))

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.ptq_close(self._q)
                self._lib.ptq_destroy(self._q)
                self._q = None
        except Exception:
            pass


# ---------------------------------------------------------------------------
# tensor file io (save_load_util.cc analog)
# ---------------------------------------------------------------------------
def save_tensors(path: str, tensors: dict) -> None:
    """Write {name: np.ndarray} as one combined PTNT file (CRC-checked)."""
    L = lib()
    items = [(n, np.ascontiguousarray(a)) for n, a in tensors.items()]
    if L is None:
        return _py_save(path, items)
    n = len(items)
    names = (ctypes.c_char_p * n)(*[k.encode() for k, _ in items])
    dtypes = (ctypes.c_char_p * n)(*[str(a.dtype).encode()
                                     for _, a in items])
    ndims = (ctypes.c_int * n)(*[a.ndim for _, a in items])
    dims_flat = []
    for _, a in items:
        dims_flat.extend(a.shape)
    dims = (ctypes.c_int64 * len(dims_flat))(*dims_flat)
    nbytes = (ctypes.c_uint64 * n)(*[a.nbytes for _, a in items])
    bufs = (ctypes.c_char_p * n)(*[a.tobytes() for _, a in items])
    rc = L.ptio_save(path.encode(), n, names, dtypes, ndims, dims,
                     nbytes, bufs)
    if rc != 0:
        raise IOError(f"ptio_save failed with {rc} for {path}")


def load_tensors(path: str) -> dict:
    """Read a PTNT file back into {name: np.ndarray}."""
    L = lib()
    if L is None:
        return _py_load(path)
    h = L.ptio_open(path.encode())
    if not h:
        raise IOError(f"not a PTNT file: {path}")
    out = {}
    try:
        while True:
            rc = L.ptio_next(h)
            if rc == 0:
                break
            if rc == -3:
                raise IOError(f"CRC mismatch in {path} (corrupt)")
            if rc < 0:
                raise IOError(f"truncated PTNT file: {path}")
            name = L.ptio_name(h).decode()
            dtype = L.ptio_dtype(h).decode()
            nd = L.ptio_ndim(h)
            dims = [L.ptio_dims(h)[i] for i in range(nd)]
            nb = L.ptio_nbytes(h)
            raw = ctypes.string_at(L.ptio_data(h), nb)
            out[name] = np.frombuffer(raw, dtype=dtype).reshape(dims).copy()
    finally:
        L.ptio_close(h)
    return out


# ---------------------------------------------------------------------------
# pure-Python fallback writing the IDENTICAL format
# ---------------------------------------------------------------------------
import struct
import zlib

_MAGIC = b"PTNT0001"


def _py_save(path, items):
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(items)))
        for name, a in items:
            nb = name.encode()
            db = str(a.dtype).encode()
            f.write(struct.pack("<I", len(nb)) + nb)
            f.write(struct.pack("<I", len(db)) + db)
            f.write(struct.pack("<I", a.ndim))
            for d in a.shape:
                f.write(struct.pack("<q", d))
            raw = a.tobytes()
            f.write(struct.pack("<Q", len(raw)))
            f.write(raw)
            f.write(struct.pack("<I", zlib.crc32(raw) & 0xFFFFFFFF))


def _py_load(path):
    out = {}
    with open(path, "rb") as f:
        if f.read(8) != _MAGIC:
            raise IOError(f"not a PTNT file: {path}")
        (n,) = struct.unpack("<I", f.read(4))
        for _ in range(n):
            (nl,) = struct.unpack("<I", f.read(4))
            name = f.read(nl).decode()
            (dl,) = struct.unpack("<I", f.read(4))
            dtype = f.read(dl).decode()
            (nd,) = struct.unpack("<I", f.read(4))
            dims = [struct.unpack("<q", f.read(8))[0] for _ in range(nd)]
            (nb,) = struct.unpack("<Q", f.read(8))
            raw = f.read(nb)
            (crc,) = struct.unpack("<I", f.read(4))
            if crc != (zlib.crc32(raw) & 0xFFFFFFFF):
                raise IOError(f"CRC mismatch in {path} (corrupt)")
            out[name] = np.frombuffer(raw, dtype=dtype).reshape(dims).copy()
    return out

"""paddle.metric — Accuracy / Precision / Recall / Auc."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc  # noqa: F401


def accuracy(input, label, k=1):
    """Functional top-k accuracy over a batch (metric_op.py accuracy)."""
    m = Accuracy(topk=(k,))
    return m.update(m.compute(input, label))


def auc(input, label, curve="ROC", num_thresholds=4095):
    """Functional AUC (reference paddle.metric.auc -> fluid
    layers.auc); static-graph layer when called under a program guard."""
    from ..static import layers
    return layers.auc(input, label, curve=curve,
                      num_thresholds=num_thresholds)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Functional chunk evaluation (reference paddle.metric.chunk_eval
    -> fluid layers.chunk_eval)."""
    from ..static import layers
    return layers.chunk_eval(input, label, chunk_scheme, num_chunk_types,
                             excluded_chunk_types=excluded_chunk_types,
                             seq_length=seq_length)


def mean_iou(input, label, num_classes):
    """Functional mean-IoU (reference paddle.metric.mean_iou -> fluid
    layers.mean_iou)."""
    from ..static import layers
    return layers.mean_iou(input, label, num_classes)

"""paddle.metric — Accuracy / Precision / Recall / Auc."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc  # noqa: F401


def accuracy(input, label, k=1):
    """Functional top-k accuracy over a batch (metric_op.py accuracy)."""
    m = Accuracy(topk=(k,))
    return m.update(m.compute(input, label))

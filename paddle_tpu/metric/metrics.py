"""paddle.metric — training metrics.

Reference: /root/reference/python/paddle/metric/metrics.py (Metric base,
Accuracy, Precision, Recall, Auc).  Host-side accumulation over numpy — the
per-batch `compute` piece is traceable and can run inside the jitted step;
`update` consumes its numpy result (same split the reference uses between
the metric op and the Python accumulator).
"""
from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np

from ..io.framework_io import _to_numpy as _np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional traceable pre-processing of (pred, label) whose outputs
        feed update(); default passes through."""
        return args


class Accuracy(Metric):
    """Top-k accuracy. update() takes the per-sample correctness matrix
    produced by compute() (shape [batch, topk])."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:  # one-hot / index column
            if label.shape[-1] == 1:
                label = label[..., 0]
            else:
                label = label.argmax(-1)
        correct = (idx == label[..., None]).astype(np.float32)
        return correct

    def update(self, correct):
        correct = _np(correct).reshape(-1, correct.shape[-1])
        num = correct.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(correct[:, :k].sum())
        self.count += num
        res = [self.total[i] / max(1, self.count)
               for i in range(len(self.topk))]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(1, self.count) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision: TP / (TP + FP).  pred is probability of class 1."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall: TP / (TP + FN)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via the reference's thresholded-bucket trapezoid estimate
    (metrics.py Auc; same algorithm as the auc op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.curve = curve
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] >= 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        n = self.num_thresholds + 1
        pos_mask = labels != 0
        self._stat_pos += np.bincount(bins[pos_mask], minlength=n)
        self._stat_neg += np.bincount(bins[~pos_mask], minlength=n)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        idx = self.num_thresholds
        while idx >= 0:
            new_pos = tot_pos + self._stat_pos[idx]
            new_neg = tot_neg + self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, new_neg, tot_pos, new_pos)
            tot_pos, tot_neg = new_pos, new_neg
            idx -= 1
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name

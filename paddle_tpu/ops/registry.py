"""Operator registry & kernel dispatch.

TPU-native analog of the reference op registry
(/root/reference/paddle/fluid/framework/op_registry.h:230 REGISTER_OPERATOR,
 op_info.h OpInfoMap, operator.cc:1017/1141 kernel dispatch by OpKernelType).

Design (deliberately different from the reference):
  * A kernel is a pure, traceable JAX function `kernel(ins, attrs, ctx)` —
    there is no per-(place,dtype,layout,library) kernel table.  One traceable
    definition serves every place: the executor composes all kernels of a
    block and `jit`s the whole thing, so XLA does the per-backend lowering
    that OpKernelType dispatch did in the reference (SURVEY.md §7 stage 3).
  * Gradient ops are auto-derived: registering `foo` with grad="auto" also
    registers `foo_grad` whose kernel is `jax.vjp` of the forward kernel
    (replacing the per-op GradOpMaker C++ classes,
     /root/reference/paddle/fluid/framework/grad_op_desc_maker.h).  Ops with
    bespoke efficient gradients can pass an explicit grad kernel.
  * RNG-consuming ops draw keys from `ctx.key(attrs)` which folds the op's
    build-time uid into the per-step seed — grad ops replay the same key, so
    dropout masks match between forward and backward (the reference solves
    this by caching masks in memory; on TPU recomputing from a counter-based
    PRNG is cheaper than an HBM round-trip).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["OpInfo", "register_op", "get_op_info", "all_ops", "OpContext",
           "Slot"]


class Slot:
    """Input/output slot declaration."""

    def __init__(self, name: str, duplicable: bool = False,
                 optional: bool = False, no_grad: bool = False):
        self.name = name
        self.duplicable = duplicable
        self.optional = optional
        # no_grad: this slot never receives/produces a gradient (e.g. int
        # indices, shape tensors)
        self.no_grad = no_grad

    @staticmethod
    def parse(spec) -> "Slot":
        if isinstance(spec, Slot):
            return spec
        # string spec: "X", "X*" (duplicable), "X?" (optional), "X!" (no_grad)
        name = spec
        dup = opt = ng = False
        while name and name[-1] in "*?!":
            c, name = name[-1], name[:-1]
            dup |= c == "*"
            opt |= c == "?"
            ng |= c == "!"
        return Slot(name, dup, opt, ng)


class OpContext:
    """Per-execution context handed to kernels (ExecutionContext analog,
    /root/reference/paddle/fluid/framework/operator.h:243) — carries the step
    RNG seed, test-mode flag, and mesh axis names for collective lowering."""

    def __init__(self, seed=0, is_test: bool = False,
                 mesh_axes: Sequence[str] = (), dist_info=None):
        self.seed = seed  # python int or traced scalar
        self.is_test = is_test
        self.mesh_axes = tuple(mesh_axes)
        # dist_info: ring_id -> axis name(s) mapping for collective ops
        self.dist_info = dist_info or {}

    def key(self, attrs: Dict[str, Any]):
        uid = attrs.get("fwd_uid", attrs.get("op_uid", 0))
        seed = attrs.get("seed", 0) or self.seed
        base = jax.random.PRNGKey(jnp.asarray(seed, jnp.uint32))
        return jax.random.fold_in(base, jnp.uint32(uid))

    def collective_axes(self, ring_id: int):
        """Map a reference-style ring_id onto mesh axis name(s).  Ring 0 is
        the data-parallel world by convention (collective_helper.h:62 —
        NCCLCommContext ring registry).  Unknown rings (user groups from
        new_group) use the "default" binding when one is set — under a
        multi-axis mesh that keeps them on the dp world instead of
        silently spanning every axis."""
        if ring_id in self.dist_info:
            return self.dist_info[ring_id]
        # A ring minted by new_group(ranks=[...]) is a strict
        # sub-communicator; widening it to the dp world / full mesh would
        # silently reduce over ranks outside the group.  Refuse instead.
        from ..distributed.collective import _groups, _world_size
        g = _groups.get(ring_id)
        if g is not None and g.ranks is not None and \
                sorted(g.ranks) != list(range(_world_size())):
            raise NotImplementedError(
                f"collective over sub-group ring_id={ring_id} "
                f"(ranks={g.ranks}) has no mesh-axis binding: register one "
                f"in OpContext.dist_info (CompiledProgram ring registry) "
                f"rather than widening the collective to the whole mesh")
        if "default" in self.dist_info:
            return self.dist_info["default"]
        return self.mesh_axes or None


class OpInfo:
    def __init__(self, type: str, kernel: Callable,
                 inputs: Sequence, outputs: Sequence,
                 grad: Optional[Any] = "auto",
                 side_effect: bool = False,
                 infer_shape: Optional[Callable] = None):
        self.type = type
        self.kernel = kernel
        self.inputs: List[Slot] = [Slot.parse(s) for s in inputs]
        self.outputs: List[Slot] = [Slot.parse(s) for s in outputs]
        self.grad = grad
        self.side_effect = side_effect
        self.infer_shape = infer_shape

    @property
    def has_grad(self):
        return self.grad is not None

    def grad_op_type(self):
        return self.type + "_grad"

    def input_slot(self, name):
        for s in self.inputs:
            if s.name == name:
                return s
        return None


_REGISTRY: Dict[str, OpInfo] = {}


def get_op_info(type: str) -> Optional[OpInfo]:
    return _REGISTRY.get(type)


def all_ops() -> List[str]:
    return sorted(_REGISTRY)


def register_op(type: str, inputs: Sequence, outputs: Sequence,
                grad: Any = "auto", side_effect: bool = False,
                infer_shape: Optional[Callable] = None):
    """Decorator: register a forward kernel.

    grad: "auto"  -> derive `<type>_grad` via jax.vjp of this kernel
          None    -> op is non-differentiable (REGISTER_OP_WITHOUT_GRADIENT)
          callable-> explicit grad kernel with signature kernel(ins,attrs,ctx);
                     its slots follow the auto-grad convention below.
    """

    def deco(fn):
        info = OpInfo(type, fn, inputs, outputs, grad, side_effect, infer_shape)
        _REGISTRY[type] = info
        if grad is not None:
            _register_grad(info)
        return fn

    return deco


# ---------------------------------------------------------------------------
# auto-generated gradient ops
# ---------------------------------------------------------------------------
# Grad op slot convention (matches the reference's default GradOpMaker):
#   inputs : every forward input slot (same names)
#            every forward output slot (values may be needed by custom grads)
#            "<out>@GRAD" for every forward output slot — differentiable,
#            so second-order cotangents can flow through it
#   outputs: "<in>@GRAD" for every forward input slot with no_grad=False
#
# `depth` registers grads-of-grads: foo_grad itself gets an auto-vjp
# foo_grad_grad one level deep (the reference's DoubleGradMaker pattern,
# e.g. conv_op.cc Conv2DDoubleGradMaker) — enough for gradient-penalty
# training and paddle.grad(create_graph=True) over the static path.
def _register_grad(fwd: OpInfo, depth: int = 1):
    gtype = fwd.grad_op_type()
    g_inputs = ([Slot(s.name, s.duplicable, True, s.no_grad) for s in fwd.inputs]
                # forward outputs stay differentiable inputs of the grad op:
                # custom grad kernels (flash attention bwd) consume them, and
                # the chain rule needs their cotangent; auto-vjp grad kernels
                # ignore them so their cotangent is zero
                + [Slot(s.name, s.duplicable, True, s.no_grad)
                   for s in fwd.outputs]
                + [Slot(s.name + "@GRAD", s.duplicable, True, s.no_grad)
                   for s in fwd.outputs])
    g_outputs = [Slot(s.name + "@GRAD", s.duplicable, True)
                 for s in fwd.inputs if not s.no_grad]

    if callable(fwd.grad):
        kernel = fwd.grad
    else:
        kernel = _make_vjp_grad_kernel(fwd)

    ginfo = OpInfo(gtype, kernel, g_inputs, g_outputs,
                   grad=("auto" if depth > 0 else None))
    _REGISTRY[gtype] = ginfo
    if depth > 0:
        _register_grad(ginfo, depth=depth - 1)


def _is_diff(x):
    if x is None:
        return False
    # pytree values (TensorArrayVal and friends) are differentiable when
    # any of their array leaves is — jnp.asarray would choke on them
    if jax.tree_util.all_leaves([x]):
        return jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    return any(jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)
               for leaf in jax.tree_util.tree_leaves(x))


def _make_vjp_grad_kernel(fwd: OpInfo):
    """Build a grad kernel that re-traces the forward under jax.vjp.  Inside a
    whole-block jit, XLA CSE merges the replayed forward with the original, so
    this costs nothing extra at runtime."""

    def grad_kernel(ins, attrs, ctx):
        # split differentiable vs pass-through forward inputs
        fwd_vals = {}
        for slot in fwd.inputs:
            fwd_vals[slot.name] = ins.get(slot.name)
        diff_names = []
        for slot in fwd.inputs:
            v = fwd_vals[slot.name]
            if slot.no_grad or v is None:
                continue
            if slot.duplicable:
                if any(_is_diff(x) for x in v):
                    diff_names.append(slot.name)
            elif _is_diff(v):
                diff_names.append(slot.name)

        def forward(diff_ins):
            merged = dict(fwd_vals)
            merged.update(diff_ins)
            attrs2 = dict(attrs)
            attrs2.setdefault("fwd_uid", attrs.get("fwd_uid",
                                                   attrs.get("op_uid", 0)))
            outs = fwd.kernel(merged, attrs2, ctx)
            # cotangents only flow through floating outputs — integer
            # outputs (top_k Indices, argsort Indices) would need float0
            # cotangents, so exclude them from the vjp.  Duplicable slots
            # are filtered PER ELEMENT (a while op's Out list mixes float
            # state with its bool condition — the float entries must still
            # carry gradient), keyed by position so the cotangent
            # assembly below can realign.
            flat = {}
            for slot in fwd.outputs:
                o = outs.get(slot.name)
                if o is None:
                    continue
                if isinstance(o, (list, tuple)):
                    sel = {str(i): x for i, x in enumerate(o)
                           if _is_diff(x)}
                    if sel:
                        flat[slot.name] = sel
                elif _is_diff(o):
                    flat[slot.name] = o
            return flat

        diff_ins = {n: fwd_vals[n] for n in diff_names}
        outs, vjp_fn = jax.vjp(forward, diff_ins)

        # assemble output cotangents; default zeros for missing grads
        cts = {}
        for slot in fwd.outputs:
            if slot.name not in outs:
                continue
            g = ins.get(slot.name + "@GRAD")
            ref = outs[slot.name]
            if isinstance(ref, dict):
                # duplicable slot: float elements keyed by position
                gs = {}
                for k, r in ref.items():
                    i = int(k)
                    gi = (g[i] if g is not None and i < len(g)
                          and g[i] is not None else None)
                    gs[k] = gi if gi is not None else \
                        jax.tree_util.tree_map(jnp.zeros_like, r)
                cts[slot.name] = gs
            else:
                cts[slot.name] = g if g is not None else \
                    jax.tree_util.tree_map(jnp.zeros_like, ref)

        (din,) = vjp_fn(cts)
        result = {}
        for slot in fwd.inputs:
            if slot.no_grad:
                continue
            gname = slot.name + "@GRAD"
            if slot.name in din:
                result[gname] = din[slot.name]
        return result

    return grad_kernel


# ---------------------------------------------------------------------------
# kernel invocation helper used by both executors (static trace & dygraph)
# ---------------------------------------------------------------------------
def run_kernel(op_type: str, ins: Dict[str, Any], attrs: Dict[str, Any],
               ctx: OpContext) -> Dict[str, Any]:
    info = get_op_info(op_type)
    if info is None:
        raise NotImplementedError(f"no kernel registered for op {op_type!r}")
    return info.kernel(ins, attrs, ctx)

"""Attention kernels: flash attention (Pallas/TPU) + ring attention
(sequence parallelism over a mesh axis).

Reference capability: the reference's attention exists only as fused
inference kernels (operators/fused/multihead_matmul_op.cu,
math/bert_encoder_functor.cu) and it has NO long-context story
(SURVEY.md §5.7).  This module is the TPU-native upgrade the north star
requires:

  * `flash_attention` — block-wise online-softmax attention as a Pallas TPU
    kernel (VMEM-tiled, MXU matmuls, O(S) memory instead of the O(S^2)
    scores matrix).  Forward is the Pallas kernel; backward recomputes
    blocks through the reference formulation (jax.vjp), i.e. activation
    memory stays O(S).
  * `ring_attention` — sequence-parallel attention: each device of a mesh
    axis holds a sequence shard; K/V shards rotate around the ring via
    lax.ppermute while online-softmax statistics accumulate (RingAttention
    / blockwise-parallel-transformer pattern).  Compute overlaps the ICI
    transfer of the next shard.

Both degrade gracefully off-TPU: Pallas runs in interpreter mode on CPU,
ring attention is pure jax and runs under any shard_map mesh.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "ring_attention", "reference_attention",
           "enable_flash_attention", "flash_enabled", "use_flash_for",
           "set_flash_min_seq_len"]

# reserved ring id binding the sequence-parallel mesh axis (user groups from
# paddle.distributed.new_group start at 1 and must not collide)
SP_RING_ID = 101

# mode: "auto" dispatches per call on sequence length.  Measured on the
# real v5e chip (r5, BERT-base bench): XLA's fused attention beats this
# Pallas kernel at EVERY length where both fit — 0.66x at seq 512,
# 0.73x at 2048, 0.75x at 4096 — so auto mode keeps the XLA path
# through the measured range and selects flash only from 8192 up, where
# the materialized [B,H,S,S] scores stop fitting HBM and the
# memory-frugal kernel is the difference between running and OOM.
# Explicit control: enable_flash_attention / FLAGS_use_flash_attention
# / the flash_min_seq_len flag; tools/tune_flash.py re-evaluates the
# crossover from block-size sweeps on hardware.
_FLASH_STATE = {"mode": "auto", "min_seq_len": 8192}


def enable_flash_attention(on: bool = True):
    """Force MultiHeadAttention / scaled_dot_product_attention through
    (on=True) or away from (on=False) the Pallas flash kernel,
    overriding the seq-length auto-dispatch
    (FLAGS_use_flash_attention analog)."""
    _FLASH_STATE["mode"] = "on" if on else "off"


def set_flash_min_seq_len(n: int):
    """Auto-dispatch crossover: sequences >= n take the flash kernel."""
    _FLASH_STATE["min_seq_len"] = int(n)


def flash_enabled() -> bool:
    """True when flash is FORCED on (legacy probe; prefer
    use_flash_for(seq_len))."""
    if _FLASH_STATE["mode"] == "on":
        return True
    from ..core.flags import flag
    return bool(flag("use_flash_attention", False))


def use_flash_for(seq_len) -> bool:
    """Per-callsite dispatch decision: forced on/off wins; in auto mode a
    STATIC sequence length >= the crossover threshold selects flash."""
    if _FLASH_STATE["mode"] == "on":
        return True
    from ..core.flags import flag
    if bool(flag("use_flash_attention", False)):
        return True
    if _FLASH_STATE["mode"] == "off":
        return False
    if seq_len is None or not isinstance(seq_len, int) or seq_len <= 0:
        return False  # dynamic/unknown seq: keep the XLA path
    thr = int(flag("flash_min_seq_len", _FLASH_STATE["min_seq_len"]))
    return seq_len >= thr


# ---------------------------------------------------------------------------
# reference (used for VJP and as the non-TPU fallback)
# ---------------------------------------------------------------------------
def reference_attention(q, k, v, bias=None, causal=False, scale=None):
    """Plain softmax(QK^T)V.  q,k,v: [B, H, S, D] (float)."""
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash attention (forward kernel)
# ---------------------------------------------------------------------------
def _causal_mask_block(s, qi, kb, block_q, block_k, sk, sq):
    """Apply the bottom-right-aligned causal mask to one [block_q, block_k]
    logits tile: query i attends keys <= i + (sk - sq) — matches
    reference_attention's tril(k=sk-sq).  Shared by fwd and both bwd
    kernels so the alignment can never drift between them."""
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0) + (sk - sq)
    kpos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, sk,
                      sq, causal, scale, block_q):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)  # query-block index (grid: B, H, Sq/block_q)
    # operands stay in their storage dtype (bf16 under AMP) so the MXU runs
    # at low-precision rate; accumulation is fp32 via preferred_element_type
    # and the scale folds into the fp32 scores
    q = q_ref[0, 0, :, :]                              # [block_q, d]

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    n_kb = sk // block_k

    def body(kb, carry):
        m, l, acc = carry
        ks = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        vs = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask_block(s, qi, kb, block_q, block_k, sk, sq)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows (m_new == -inf): exp(-inf - -inf) → nan
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(vs.dtype), vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # skip key blocks entirely above the (bottom-right) diagonal
        n_needed = jnp.minimum(
            n_kb, ((qi + 1) * block_q + (sk - sq) + block_k - 1) // block_k)
        m, l, acc = jax.lax.fori_loop(0, n_needed, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m, l, acc))

    o_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # logsumexp per query row, saved for the blockwise backward
    lse = jnp.where(jnp.isfinite(m), m + jnp.log(jnp.maximum(l, 1e-30)),
                    -jnp.inf)
    lse_ref[0, 0, :, :] = lse


def _fit_block(n, want):
    """Largest block size <= `want` that tiles `n` evenly and satisfies the
    Mosaic sublane constraint (multiple of 8); None if impossible.  A bare
    min() would reroute e.g. sq=384 with want=256 to the O(S^2) fallback
    even though 128 tiles it."""
    for b in range(min(want, n), 7, -1):
        if n % b == 0 and b % 8 == 0:
            return b
    return None


def _tiles_ok(sq, sk, block_q, block_k):
    return _fit_block(sq, block_q) is not None and \
        _fit_block(sk, block_k) is not None


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (out, lse); lse is [B, H, Sq, 1] float32."""
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k, sk=sk,
                               sq=sq, causal=causal, scale=scale,
                               block_q=block_q)
    grid = (b, h, sq // block_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pallas flash attention (backward kernels)
#
# Standard flash-attention backward: probabilities are recomputed per
# (q-block, k-block) tile from q, k and the saved logsumexp, so nothing
# O(S^2) is ever materialized.  Two kernels because TPU has no atomics:
#   dq  — grid over q blocks, inner loop over k blocks
#   dkv — grid over k blocks, inner loop over q blocks
# Both need D = rowsum(dO * O) (the softmax-jacobian correction), computed
# once outside as an elementwise reduce that XLA fuses.
# ---------------------------------------------------------------------------
def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                         dq_ref, *, block_k, sk, sq, causal, scale,
                         block_q):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :]                              # [bq, d] storage dtype
    do = do_ref[0, 0, :, :]                            # [bq, d]
    lse = lse_ref[0, 0, :, :]                          # [bq, 1] f32
    dd = dd_ref[0, 0, :, :]                            # [bq, 1] f32
    safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)

    n_kb = sk // block_k

    def body(kb, dq):
        ks = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        vs = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask_block(s, qi, kb, block_q, block_k, sk, sq)
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - safe_lse), 0.0)
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk] f32
        ds = p * (dp - dd)                               # [bq, bk] f32
        return dq + jax.lax.dot_general(
            ds.astype(ks.dtype), ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    if causal:
        n_needed = jnp.minimum(
            n_kb, ((qi + 1) * block_q + (sk - sq) + block_k - 1) // block_k)
        dq = jax.lax.fori_loop(0, n_needed, body, dq)
    else:
        dq = jax.lax.fori_loop(0, n_kb, body, dq)
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                          dk_ref, dv_ref, *, block_k, sk, sq, causal,
                          scale, block_q):
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    ks = k_ref[0, 0, :, :]                              # [bk, d] storage dtype
    vs = v_ref[0, 0, :, :]                              # [bk, d]

    n_qb = sq // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qi * block_q, block_q), :]   # [bq, d]
        do = do_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        dd = dd_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        safe_lse = jnp.where(jnp.isfinite(lse), lse, 0.0)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            s = _causal_mask_block(s, qi, kb, block_q, block_k, sk, sq)
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - safe_lse), 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk] f32
        ds = (p * (dp - dd)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bk, d]
        return dk, dv

    dk = jnp.zeros((block_k, ks.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, vs.shape[-1]), jnp.float32)
    if causal:
        # first q block that can see this k block: q_pos >= k_pos-(sk-sq)
        start = jnp.maximum(0, (kb * block_k - (sk - sq)) // block_q)
        dk, dv = jax.lax.fori_loop(start, n_qb, body, (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(0, n_qb, body, (dk, dv))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
               interpret):
    # NOTE: like the forward, the non-gridded operands (full K/V here, full
    # Q/dO/lse in the dkv kernel) are staged whole in VMEM, which caps the
    # single-chip sequence length at roughly S*D*4B ≲ a few MB (S ≈ 8-16k
    # at D=64).  Longer sequences are the ring_attention path's job; if a
    # single-chip >16k case appears, move these operands to ANY memory
    # space with explicit DMA per block.
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)

    # D = rowsum(dO * O): elementwise + reduce, XLA fuses; O(S) memory
    dd = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                 axis=-1, keepdims=True)                 # [b, h, sq, 1]

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0))
    qrow = pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0))
    full_q = pl.BlockSpec((1, 1, sq, d), lambda bi, hi, i: (bi, hi, 0, 0))
    full_qrow = pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, i: (bi, hi, 0, 0))
    full_k = pl.BlockSpec((1, 1, sk, d), lambda bi, hi, i: (bi, hi, 0, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0))

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_k=block_k, sk=sk, sq=sq, causal=causal,
        scale=scale, block_q=block_q)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, sq // block_q),
        in_specs=[qspec, full_k, full_k, qspec, qrow, qrow],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, dd)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_k=block_k, sk=sk, sq=sq, causal=causal,
        scale=scale, block_q=block_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, sk // block_k),
        in_specs=[full_q, kspec, kspec, full_q, full_qrow, full_qrow],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, dd)
    return dq, dk, dv


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    if not _tiles_ok(q.shape[2], k.shape[2], block_q, block_k):
        return reference_attention(q, k, v, causal=causal, scale=scale)
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret=not _on_tpu())
    return out


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    if not _tiles_ok(q.shape[2], k.shape[2], block_q, block_k):
        out = reference_attention(q, k, v, causal=causal, scale=scale)
        return out, (q, k, v, None, None)
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                          interpret=not _on_tpu())
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if lse is None:
        # non-tiling fallback shapes: reference vjp (small/irregular only)
        _, vjp = jax.vjp(lambda q_, k_, v_: reference_attention(
            q_, k_, v_, causal=causal, scale=scale), q, k, v)
        return vjp(g)
    return _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q,
                      block_k, interpret=not _on_tpu())


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    block_q=256, block_k=512):
    """Flash attention over [B, H, S, D] tensors.  `bias` forces the
    reference path (arbitrary bias breaks the blockwise max-trick bound
    chosen here; padding masks should be folded into K by the caller).

    Fully-masked rows (causal with sq > sk leaves the first sq-sk queries
    without any visible key) output ZERO here, while the reference path's
    finfo.min masking degrades to a uniform average of V — both values
    are semantically undefined; don't consume those rows."""
    if bias is not None:
        return reference_attention(q, k, v, bias=bias, causal=causal,
                                   scale=scale)
    from ..core.flags import flag
    block_q = int(flag("flash_block_q", block_q))
    block_k = int(flag("flash_block_k", block_k))
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    # the Pallas kernels keep operands in storage dtype for MXU rate, so
    # mixed q/k/v dtypes (bf16 queries over an fp32 KV cache) promote to a
    # common dtype first — lax.dot_general requires identical operands
    cdt = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype), v.dtype)
    q, k, v = q.astype(cdt), k.astype(cdt), v.astype(cdt)
    return _flash(q, k, v, causal, scale, block_q, block_k)


# ---------------------------------------------------------------------------
# ring attention (sequence parallel)
# ---------------------------------------------------------------------------
def ring_attention(q, k, v, axis_name: str, causal=False, scale=None):
    """Sequence-parallel attention inside shard_map: every device holds
    [B, H, S/n, D] shards (sequence dim sharded over `axis_name`); K/V
    rotate around the ring while online-softmax stats accumulate.

    Causal masking uses GLOBAL positions: device r's queries are rows
    [r*S_loc, (r+1)*S_loc); the k-th rotation holds keys of device
    (r - step) % n.
    """
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]

    def step_fn(carry, step):
        m, l, acc, ks, vs = carry
        src = (me - step) % n  # whose keys we currently hold
        # operands stay in storage dtype (bf16 MXU rate); scores accumulate
        # fp32 and the scale folds in afterwards
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 3)
            qp = me * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 2)
            s = jnp.where(qp >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - safe_m), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vs.dtype), vs,
            preferred_element_type=jnp.float32)
        # rotate K/V to the next device (overlaps with next step's compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        ks = jax.lax.ppermute(ks, axis_name, perm)
        vs = jax.lax.ppermute(vs, axis_name, perm)
        return (m_new, l_new, acc_new, ks, vs), None

    b, h = q.shape[0], q.shape[1]
    m0 = jnp.full((b, h, s_loc, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    a0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, a0, k, v), jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

"""Attention kernels: flash attention (Pallas/TPU) + ring attention
(sequence parallelism over a mesh axis).

Reference capability: the reference's attention exists only as fused
inference kernels (operators/fused/multihead_matmul_op.cu,
math/bert_encoder_functor.cu) and it has NO long-context story
(SURVEY.md §5.7).  This module is the TPU-native upgrade the north star
requires:

  * `flash_attention` — block-wise online-softmax attention as a Pallas TPU
    kernel (VMEM-tiled, MXU matmuls, O(S) memory instead of the O(S^2)
    scores matrix).  Forward is the Pallas kernel; backward recomputes
    blocks through the reference formulation (jax.vjp), i.e. activation
    memory stays O(S).
  * `ring_attention` — sequence-parallel attention: each device of a mesh
    axis holds a sequence shard; K/V shards rotate around the ring via
    lax.ppermute while online-softmax statistics accumulate (RingAttention
    / blockwise-parallel-transformer pattern).  Compute overlaps the ICI
    transfer of the next shard.

Both degrade gracefully off-TPU: Pallas runs in interpreter mode on CPU,
ring attention is pure jax and runs under any shard_map mesh.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "ring_attention", "reference_attention",
           "enable_flash_attention", "flash_enabled"]

# reserved ring id binding the sequence-parallel mesh axis (user groups from
# paddle.distributed.new_group start at 1 and must not collide)
SP_RING_ID = 101

_FLASH_STATE = {"enabled": False}


def enable_flash_attention(on: bool = True):
    """Route MultiHeadAttention / scaled_dot_product_attention through the
    Pallas flash kernel (FLAGS_use_flash_attention analog)."""
    _FLASH_STATE["enabled"] = bool(on)


def flash_enabled() -> bool:
    if _FLASH_STATE["enabled"]:
        return True
    from ..core.flags import flag
    return bool(flag("use_flash_attention", False))


# ---------------------------------------------------------------------------
# reference (used for VJP and as the non-TPU fallback)
# ---------------------------------------------------------------------------
def reference_attention(q, k, v, bias=None, causal=False, scale=None):
    """Plain softmax(QK^T)V.  q,k,v: [B, H, S, D] (float)."""
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash attention (forward kernel)
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, sk, sq,
                      causal, scale, block_q):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)  # query-block index (grid: B, H, Sq/block_q)
    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale  # [block_q, d]

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    n_kb = sk // block_k

    def body(kb, carry):
        m, l, acc = carry
        ks = k_ref[0, 0, pl.ds(kb * block_k, block_k), :] \
            .astype(jnp.float32)
        vs = v_ref[0, 0, pl.ds(kb * block_k, block_k), :] \
            .astype(jnp.float32)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            # bottom-right aligned (matches reference_attention's
            # tril(k=sk-sq)): query i attends keys <= i + (sk - sq)
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (sk - sq)
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows (m_new == -inf): exp(-inf - -inf) → nan
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # skip key blocks entirely above the (bottom-right) diagonal
        n_needed = jnp.minimum(
            n_kb, ((qi + 1) * block_q + (sk - sq) + block_k - 1) // block_k)
        m, l, acc = jax.lax.fori_loop(0, n_needed, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m, l, acc))

    o_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # fall back unless blocks tile evenly AND respect the f32 sublane
    # multiple of 8 (Mosaic lowering requirement on real TPU)
    if sq % block_q or sk % block_k or block_q % 8 or block_k % 8:
        return reference_attention(q, k, v, causal=causal, scale=scale)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k, sk=sk,
                               sq=sq, causal=causal, scale=scale,
                               block_q=block_q)
    grid = (b, h, sq // block_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v)


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                      interpret=not _on_tpu())


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    out = _flash(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    # backward recomputes through the reference formulation block-free;
    # activation memory between fwd and bwd stays O(S)
    _, vjp = jax.vjp(lambda q_, k_, v_: reference_attention(
        q_, k_, v_, causal=causal, scale=scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, bias=None, causal=False, scale=None,
                    block_q=128, block_k=128):
    """Flash attention over [B, H, S, D] tensors.  `bias` forces the
    reference path (arbitrary bias breaks the blockwise max-trick bound
    chosen here; padding masks should be folded into K by the caller)."""
    if bias is not None:
        return reference_attention(q, k, v, bias=bias, causal=causal,
                                   scale=scale)
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    return _flash(q, k, v, causal, scale, block_q, block_k)


# ---------------------------------------------------------------------------
# ring attention (sequence parallel)
# ---------------------------------------------------------------------------
def ring_attention(q, k, v, axis_name: str, causal=False, scale=None):
    """Sequence-parallel attention inside shard_map: every device holds
    [B, H, S/n, D] shards (sequence dim sharded over `axis_name`); K/V
    rotate around the ring while online-softmax stats accumulate.

    Causal masking uses GLOBAL positions: device r's queries are rows
    [r*S_loc, (r+1)*S_loc); the k-th rotation holds keys of device
    (r - step) % n.
    """
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    qf = q.astype(jnp.float32) * scale

    def step_fn(carry, step):
        m, l, acc, ks, vs = carry
        src = (me - step) % n  # whose keys we currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, ks.astype(jnp.float32))
        if causal:
            kpos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 3)
            qp = me * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 2)
            s = jnp.where(qp >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - safe_m), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vs.astype(jnp.float32))
        # rotate K/V to the next device (overlaps with next step's compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        ks = jax.lax.ppermute(ks, axis_name, perm)
        vs = jax.lax.ppermute(vs, axis_name, perm)
        return (m_new, l_new, acc_new, ks, vs), None

    b, h = q.shape[0], q.shape[1]
    m0 = jnp.full((b, h, s_loc, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    a0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step_fn, (m0, l0, a0, k, v), jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
